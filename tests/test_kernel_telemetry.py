"""In-kernel superstep telemetry (obs.kernel threaded through the fused
engines' while-loop carries).

The contract under test: with ``record_trajectory`` enabled an engine
returns the complete per-superstep trajectory **from the fused kernel**
(one device call, one trajectory transfer per attempt — no host-stepped
loop, no per-superstep round-trips), and the numbers match the
host-stepped ``trace_attempt`` / NumPy-replay ground truths exactly.
"""

import numpy as np
import pytest

import dgc_tpu.engine.superstep as superstep_mod
from dgc_tpu.engine.base import AttemptStatus
from dgc_tpu.engine.compact import CompactFrontierEngine
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.generators import (
    generate_random_graph_fast,
    generate_rmat_graph,
)
from dgc_tpu.utils.tracing import trace_attempt


@pytest.fixture(scope="module")
def graph_10k():
    return generate_random_graph_fast(10_000, avg_degree=8.0, seed=42)


def test_ell_inkernel_matches_trace_attempt_10k(graph_10k):
    # acceptance criterion: per-superstep active counts recorded by the
    # fused kernel match the host-stepped trace_attempt ground truth
    # EXACTLY on a seeded 10k-vertex graph — success and failure attempts
    g = graph_10k
    eng = ELLEngine(g)
    eng.record_trajectory = True
    k0 = g.max_degree + 1

    res = eng.attempt(k0)
    ref = trace_attempt(ELLEngine(g), k0)
    assert res.status == AttemptStatus.SUCCESS
    assert res.trajectory is not None
    assert res.trajectory.active.tolist() == ref.active_per_step
    assert len(res.trajectory) == res.supersteps
    assert not res.trajectory.truncated
    assert res.trajectory.fail.sum() == 0
    assert res.trajectory.active[-1] == 0

    k_fail = res.colors_used - 1
    res_f = eng.attempt(k_fail)
    ref_f = trace_attempt(ELLEngine(g), k_fail)
    assert res_f.status == AttemptStatus.FAILURE == AttemptStatus(ref_f.status)
    assert res_f.trajectory.active.tolist() == ref_f.active_per_step
    # the conflict superstep is the last recorded row
    assert res_f.trajectory.fail[-1] == 1
    assert res_f.trajectory.fail[:-1].sum() == 0


def test_ell_one_transfer_per_attempt(graph_10k, monkeypatch):
    # acceptance criterion: a fused attempt with metrics enabled performs
    # no per-superstep host transfers — the whole trajectory arrives from
    # ONE kernel invocation (per-superstep dispatch would show up here as
    # one call per superstep, the trace_attempt shape)
    g = graph_10k
    eng = ELLEngine(g)
    eng.record_trajectory = True
    calls = []
    orig = superstep_mod._attempt_kernel

    def counting_kernel(*args, **kw):
        calls.append(kw.get("record_traj"))
        return orig(*args, **kw)

    monkeypatch.setattr(superstep_mod, "_attempt_kernel", counting_kernel)
    res = eng.attempt(g.max_degree + 1)
    assert calls == [True]
    assert res.supersteps > 1  # multi-superstep attempt, single device call
    assert len(res.trajectory) == res.supersteps


def test_telemetry_off_is_inert(graph_10k):
    # record_trajectory=False (the default) must stay the production path:
    # no trajectory attached, identical colors/steps to the traced variant
    g = graph_10k
    plain = ELLEngine(g)
    res_p = plain.attempt(g.max_degree + 1)
    traced = ELLEngine(g)
    traced.record_trajectory = True
    res_t = traced.attempt(g.max_degree + 1)
    assert res_p.trajectory is None
    assert res_t.trajectory is not None
    assert res_p.supersteps == res_t.supersteps
    assert np.array_equal(res_p.colors, res_t.colors)


def test_compact_trajectory_matches_replay():
    # the staged/bucketed flagship: in-kernel actives must equal the exact
    # NumPy trajectory replay (utils.trajectory), step for step. The
    # replay logs the PRE-update frontier (including the round-1
    # specialized state), the kernel logs each superstep's POST-update
    # count — the same series shifted by one, plus the final converged row
    from dgc_tpu.utils.trajectory import record_trajectory

    g = generate_rmat_graph(1500, avg_degree=10.0, seed=7)
    replay = record_trajectory(g)
    eng = CompactFrontierEngine(g)
    eng.record_trajectory = True
    res = eng.attempt(g.max_degree + 1)
    traj = res.trajectory
    assert traj is not None
    # engine counts the round-1 specialization as a superstep; rows span
    # [first_step, supersteps)
    assert traj.first_step + len(traj) == res.supersteps
    replay_actives = [s.active for s in replay.steps]
    assert traj.active[:-1].tolist() == replay_actives[1:]
    assert traj.active[-1] == 0 and res.status == AttemptStatus.SUCCESS
    # bucket occupancy rows (hub buckets + flat total) sum to the global
    # active count every superstep
    assert traj.bucket_active is not None
    assert np.array_equal(traj.bucket_active.sum(axis=1), traj.active)


def test_compact_sweep_trajectories_and_resume():
    # the fused jump-mode pair returns BOTH attempts' trajectories in one
    # device call; the prefix-resumed confirm records only its post-resume
    # rows (first_step > 1) and the span still ends at its steps counter
    g = generate_random_graph_fast(20_000, avg_degree=8.0, seed=1)
    plain = CompactFrontierEngine(g)
    p1, p2 = plain.sweep(g.max_degree + 1)

    eng = CompactFrontierEngine(g)
    eng.record_trajectory = True
    first, second = eng.sweep(g.max_degree + 1)
    assert first.status == AttemptStatus.SUCCESS
    assert second.status == AttemptStatus.FAILURE
    # telemetry must not perturb the sweep (bit-identical contract)
    assert np.array_equal(first.colors, p1.colors)
    assert first.supersteps == p1.supersteps
    assert second.supersteps == p2.supersteps

    t1, t2 = first.trajectory, second.trajectory
    assert t1.first_step + len(t1) == first.supersteps
    assert t2.first_step + len(t2) == second.supersteps
    assert t1.fail.sum() == 0 and t2.fail[-1] == 1
    # actives are monotone non-increasing after the first couple rounds
    a = t1.active
    assert all(x >= y for x, y in zip(a[1:], a[2:]))


def test_trajectory_decode_handles_truncation():
    from dgc_tpu.obs.kernel import decode_trajectory, traj_empty

    buf = np.asarray(traj_empty(4))
    buf = buf.copy()
    buf[0] = [10, 0, -1, -1, -1, -1]
    buf[1] = [5, 0, -1, -1, -1, -1]
    t = decode_trajectory(buf, supersteps=9)  # ran past the 4-row cap
    assert t.truncated
    assert t.active.tolist() == [10, 5]
    t2 = decode_trajectory(np.asarray(traj_empty(4)), supersteps=0)
    assert len(t2) == 0 and not t2.truncated


def test_bucketed_chunked_trajectory_threads_through(graph_10k):
    # the one engine that runs an attempt as MANY device calls: the
    # trajectory buffer rides the chunked kernel's carry across calls and
    # comes back whole, without perturbing the sweep (ROADMAP telemetry
    # follow-on)
    from dgc_tpu.engine.bucketed import BucketedELLEngine

    plain = BucketedELLEngine(graph_10k, chunk_steps=4)  # force >1 chunk
    p = plain.attempt(graph_10k.max_degree + 1)
    eng = BucketedELLEngine(graph_10k, chunk_steps=4)
    eng.record_trajectory = True
    r = eng.attempt(graph_10k.max_degree + 1)
    assert np.array_equal(p.colors, r.colors)
    assert p.supersteps == r.supersteps
    t = r.trajectory
    assert t is not None
    assert t.first_step + len(t) == r.supersteps
    assert t.active[-1] == 0 and r.status == AttemptStatus.SUCCESS
    # this engine's schedule is static: one gather per bucket, every
    # superstep — the column the segmented compact engine collapses
    nb = len(eng.combined_buckets)
    assert (t.gather_calls == nb).all()


def test_compact_gather_calls_column_matches_model():
    # the in-kernel gather-call column must agree with the schedule
    # model's fused-plan count, superstep for superstep (the same
    # contract trajectories already honor for actives)
    from dgc_tpu.engine.compact import CompactFrontierEngine as Eng
    from dgc_tpu.utils.schedule_model import price_schedule
    from dgc_tpu.utils.trajectory import record_trajectory

    g = generate_rmat_graph(20_000, avg_degree=16.0, seed=0)
    eng = Eng(g)
    eng.record_trajectory = True
    res = eng.attempt(g.max_degree + 1)
    t = res.trajectory
    price = price_schedule(Eng(g), record_trajectory(g))
    # kernel rows lag the replay by one (post-update vs pre-update view,
    # see test_compact_trajectory_matches_replay); the call counts align
    # on the shared span
    assert t.gather_calls[:-1].tolist() == price.per_step_calls[1:]


def test_compact_max_unconf_column_matches_replay():
    # col 4 (max unconfirmed neighbors over active rows) must equal the
    # exact-rule replay's per-superstep maxima EXACTLY — both are
    # pre-update snapshot views, so there is no row lag here (unlike the
    # post-update actives). This is the column tune --from-manifest
    # bounds hub capture validity with.
    from dgc_tpu.engine.bucketed import BucketedELLEngine
    from dgc_tpu.engine.compact import CompactFrontierEngine as Eng
    from dgc_tpu.utils.trajectory import record_trajectory

    g = generate_rmat_graph(20_000, avg_degree=16.0, seed=0)
    eng = Eng(g)
    eng.record_trajectory = True
    t = eng.attempt(g.max_degree + 1).trajectory
    replay = record_trajectory(g)
    want = [max(st.max_unconf_per_bucket) for st in replay.steps]
    assert t.max_unconf.tolist() == want[:len(t.max_unconf)]
    # engines that don't compute the column record the -1 sentinel
    b = BucketedELLEngine(g)
    b.record_trajectory = True
    tb = b.attempt(g.max_degree + 1).trajectory
    assert (tb.max_unconf == -1).all()
    assert tb.max_unconf_bucket is None


def test_compact_max_unconf_bucket_tail_matches_replay():
    # the per-bucket tail (compact ba layout: one column per hub bucket,
    # then the flat-region total) must equal the exact-rule replay's
    # per-bucket maxima EXACTLY — each hub bucket by ITS OWN maximum
    # (what tune --from-manifest now bounds capture validity with,
    # instead of the global col-4 max), the flat slot by the max over
    # the flat buckets. Col 4 stays the tail's row-max.
    from dgc_tpu.engine.compact import CompactFrontierEngine as Eng
    from dgc_tpu.utils.trajectory import record_trajectory

    g = generate_rmat_graph(20_000, avg_degree=16.0, seed=0)
    eng = Eng(g)
    eng.record_trajectory = True
    t = eng.attempt(g.max_degree + 1).trajectory
    replay = record_trajectory(g)
    hub = eng.hub_buckets
    mub = t.max_unconf_bucket
    assert mub is not None
    assert mub.shape[1] == hub + 1       # hub buckets + flat total
    rows = min(len(mub), len(replay.steps))
    assert rows > 0
    for bi in range(hub):
        want = [st.max_unconf_per_bucket[bi] for st in replay.steps]
        assert mub[:rows, bi].tolist() == want[:rows], f"hub bucket {bi}"
    want_flat = [max(st.max_unconf_per_bucket[hub:])
                 for st in replay.steps]
    assert mub[:rows, hub].tolist() == want_flat[:rows]
    # col 4 is exactly the tail's per-row max (layout compatibility)
    assert t.max_unconf[:rows].tolist() == mub[:rows].max(axis=1).tolist()


def test_compact_timing_column_and_inertness():
    # the col-5 timing column (obs.devclock): with record_timing on, the
    # decoded trajectory carries per-superstep in-kernel wall µs (every
    # row past the first attributable, plausible magnitudes) and the
    # sweep results stay byte-identical to the timing-off kernel; with
    # timing off the column keeps its -1 fill and decodes to None
    g = generate_rmat_graph(1500, avg_degree=10.0, seed=7)
    timed = CompactFrontierEngine(g)
    timed.record_trajectory = True
    timed.record_timing = True
    t1, t2 = timed.sweep(g.max_degree + 1)

    plain = CompactFrontierEngine(g)
    plain.record_trajectory = True
    p1, p2 = plain.sweep(g.max_degree + 1)

    assert np.array_equal(t1.colors, p1.colors)
    assert t1.supersteps == p1.supersteps
    assert (t2 is None) == (p2 is None)
    if t2 is not None:
        assert np.array_equal(t2.colors, p2.colors)
        assert t2.supersteps == p2.supersteps

    su = t1.trajectory.step_us
    assert su is not None and len(su) == len(t1.trajectory)
    assert su[0] == -1                      # span head: no predecessor ts
    assert (su[1:] >= 0).all()              # every later row attributed
    total_s = su[su >= 0].sum() / 1e6
    assert 0 < total_s < 120                # sane magnitude for a CPU sweep
    # all other columns byte-identical to the timing-off recording
    assert np.array_equal(t1.trajectory.active, p1.trajectory.active)
    assert np.array_equal(t1.trajectory.fail, p1.trajectory.fail)
    assert p1.trajectory.step_us is None
    # timing without trajectories is a no-op (the _traj_kw gate)
    off = CompactFrontierEngine(g)
    off.record_timing = True
    o1, _ = off.sweep(g.max_degree + 1)
    assert o1.trajectory is None
    assert np.array_equal(o1.colors, p1.colors)


def test_timing_column_flows_to_manifest_and_report(tmp_path, capsys):
    # --superstep-timing end to end: CLI flag → engine → trajectory event
    # step_us (schema-clean) → manifest → report_run's device-time line
    import json
    import sys

    from dgc_tpu.cli import main

    sys.path.insert(0, "tools")
    import report_run
    from validate_runlog import validate_file

    log = tmp_path / "run.jsonl"
    manifest = tmp_path / "m.json"
    rc = main([
        "--node-count", "300", "--max-degree", "8", "--seed", "11",
        "--backend", "ell-compact",
        "--output-coloring", str(tmp_path / "c.json"),
        "--log-json", str(log),
        "--run-manifest", str(manifest),
        "--superstep-timing",
    ])
    capsys.readouterr()
    assert rc == 0
    assert validate_file(str(log)) == []
    trajs = [json.loads(l) for l in log.read_text().splitlines()
             if '"trajectory"' in l]
    trajs = [t for t in trajs if t.get("event") == "trajectory"]
    assert trajs and all("step_us" in t for t in trajs)
    assert any(u >= 0 for t in trajs for u in t["step_us"])
    doc = json.loads(manifest.read_text())
    assert doc["attempts"][0]["trajectory"]["step_us"]
    assert report_run.main([str(manifest)]) == 0
    assert "device time/superstep" in capsys.readouterr().out

"""Engine correctness: validity, parity, failure semantics, deadlock-freedom."""

import numpy as np
import pytest

from dgc_tpu.engine.base import AttemptStatus
from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
from dgc_tpu.engine.oracle import OracleEngine, greedy_color
from dgc_tpu.engine.reference_sim import ReferenceSimEngine
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.models.generators import generate_random_graph
from dgc_tpu.ops.validate import validate_coloring


def _minimal(engine, arrays, **kw):
    return find_minimal_coloring(
        engine, initial_k=arrays.max_degree + 1, validate=make_validator(arrays), **kw
    )


# ---------------- oracle ----------------


def test_oracle_valid_and_bounded(small_graphs):
    for g in small_graphs:
        colors = greedy_color(g)
        assert validate_coloring(g.indptr, g.indices, colors).valid
        assert colors.max() + 1 <= g.max_degree + 1


# ---------------- reference-sim ----------------


def test_reference_sim_optimized_valid(small_graphs):
    for g in small_graphs:
        res = _minimal(ReferenceSimEngine(g), g)
        assert res.minimal_colors is not None
        assert validate_coloring(g.indptr, g.indices, res.colors).valid


def test_reference_sim_progress_on_disconnected():
    # two disjoint triangles — the exact shape that deadlocks the baseline
    # reference engine (SURVEY §2.4.1); optimized semantics must finish
    g = GraphArrays.from_edge_list(
        6, np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5]])
    )
    res = ReferenceSimEngine(g, variant="optimized").attempt(3)
    assert res.status == AttemptStatus.SUCCESS


def test_reference_sim_baseline_stalls_on_disconnected():
    # the baseline variant defers vertices with no colored neighbor
    # (coloring.py:48-49); a component without the max-degree seed never
    # progresses — our sim surfaces that as STALLED instead of hanging
    g = GraphArrays.from_edge_list(
        7, np.array([[0, 1], [1, 2], [0, 2], [0, 3], [4, 5], [5, 6], [4, 6]])
    )
    res = ReferenceSimEngine(g, variant="baseline").attempt(4)
    assert res.status == AttemptStatus.STALLED


def test_reference_sim_baseline_succeeds_on_connected():
    g = GraphArrays.from_edge_list(
        5, np.array([[0, 1], [1, 2], [2, 3], [3, 4], [0, 4], [1, 3]])
    )
    res = ReferenceSimEngine(g, variant="baseline").attempt(4)
    assert res.status == AttemptStatus.SUCCESS
    assert validate_coloring(g.indptr, g.indices, res.colors).valid


# ---------------- ELL engine ----------------


def test_ell_valid_across_seeds(small_graphs):
    for g in small_graphs:
        res = _minimal(ELLEngine(g), g)
        assert res.minimal_colors is not None
        assert validate_coloring(g.indptr, g.indices, res.colors).valid


def test_ell_parity_with_reference_sim(small_graphs):
    # color-count parity ±1 against the reference's optimized semantics
    # (the contract from BASELINE.json; per-vertex equality is not expected,
    # SURVEY §7.3)
    for g in small_graphs:
        a = _minimal(ELLEngine(g), g).minimal_colors
        b = _minimal(ReferenceSimEngine(g), g).minimal_colors
        assert abs(a - b) <= 1, (a, b)


def test_ell_failure_below_minimal(small_graphs):
    g = small_graphs[0]
    res = _minimal(ELLEngine(g), g)
    below = ELLEngine(g).attempt(res.minimal_colors - 1)
    assert below.status == AttemptStatus.FAILURE


def test_ell_disconnected_progress():
    g = GraphArrays.from_edge_list(
        6, np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5]])
    )
    res = ELLEngine(g).attempt(3)
    assert res.status == AttemptStatus.SUCCESS
    assert validate_coloring(g.indptr, g.indices, res.colors).valid


def test_ell_isolated_vertices_get_color_zero():
    # reference reset pass: degree-0 vertices → color 0 (coloring.py:12-17)
    g = GraphArrays.from_neighbor_lists([[], [2], [1], []])
    res = ELLEngine(g).attempt(2)
    assert res.status == AttemptStatus.SUCCESS
    assert res.colors[0] == 0 and res.colors[3] == 0


def test_ell_deterministic(small_graphs):
    g = small_graphs[1]
    r1 = ELLEngine(g).attempt(g.max_degree + 1)
    r2 = ELLEngine(g).attempt(g.max_degree + 1)
    assert np.array_equal(r1.colors, r2.colors)


def test_ell_k_is_dynamic_no_recompile(small_graphs):
    # one compiled executable serves all k in the sweep
    import jax

    g = small_graphs[2]
    eng = ELLEngine(g)
    eng.attempt(g.max_degree + 1)
    from dgc_tpu.engine.superstep import _attempt_kernel

    sizes_before = _attempt_kernel._cache_size()
    eng.attempt(g.max_degree)
    eng.attempt(max(1, g.max_degree - 1))
    assert _attempt_kernel._cache_size() == sizes_before


def test_ell_large_k_many_planes():
    # k > 32 exercises multi-word bitmask planes (SURVEY §7.3)
    g = generate_random_graph(300, 40, seed=11)
    assert g.max_degree > 32
    res = _minimal(ELLEngine(g), g)
    assert res.minimal_colors is not None
    assert validate_coloring(g.indptr, g.indices, res.colors).valid


def test_single_vertex_and_empty_edge_graphs():
    g = GraphArrays.from_neighbor_lists([[]])
    res = ELLEngine(g).attempt(1)
    assert res.status == AttemptStatus.SUCCESS and res.colors[0] == 0
    g2 = GraphArrays.from_neighbor_lists([[], [], []])
    res2 = ELLEngine(g2).attempt(1)
    assert res2.status == AttemptStatus.SUCCESS and (res2.colors == 0).all()


def test_complete_graph_needs_v_colors():
    v = 9
    edges = np.array([[i, j] for i in range(v) for j in range(i + 1, v)])
    g = GraphArrays.from_edge_list(v, edges)
    res = _minimal(ELLEngine(g), g)
    assert res.minimal_colors == v
    assert ELLEngine(g).attempt(v - 1).status == AttemptStatus.FAILURE


def test_bipartite_two_colors():
    # even cycle: chromatic number 2; greedy first-fit finds it
    v = 12
    edges = np.array([[i, (i + 1) % v] for i in range(v)])
    g = GraphArrays.from_edge_list(v, edges)
    res = _minimal(ELLEngine(g), g)
    assert res.minimal_colors == 2


"""Fleet telemetry plane tests: W3C traceparent propagation across the
HTTP boundary and journal-replay incarnations, per-tenant usage metering
(live meter, journal fold, exact conservation), the timeseries sampler
ring + multi-window SLO burn-rate evaluator, the mesh_degrade
flight-recorder auto-dump trigger, and the new ``usage_rollup`` /
``slo_burn`` schema + validate_runlog semantics."""

import json
import time

import numpy as np
import pytest

from dgc_tpu.obs import (FlightRecorder, MetricsRegistry, RunLogger,
                         UsageMeter)
from dgc_tpu.obs.timeseries import BurnRateEvaluator, TimeseriesSampler
from dgc_tpu.obs.trace import (boundary_span_id, format_traceparent,
                               parse_traceparent)
from dgc_tpu.obs.usage import (conservation_problems, fold_journal,
                               journal_totals, payload_vertices)
from dgc_tpu.serve.netfront import NetFront, TicketJournal, scan_journal
from dgc_tpu.serve.queue import ServeFrontEnd, ServeResult
from tools.validate_runlog import validate_file

pytestmark = pytest.mark.serve

TRACE_ID = "4bf92f3577b34da6a3ce929d0e0e4736"
PARENT_ID = "00f067aa0ba902b7"
TRACEPARENT = f"00-{TRACE_ID}-{PARENT_ID}-01"


# -- no-jax front end (the test_netfront pattern) -----------------------

class _FakeAttempt:
    class _Status:
        name = "SUCCESS"

    def __init__(self, k):
        self.k = int(k)
        self.status = self._Status()
        self.supersteps = 5


class _InstantFront(ServeFrontEnd):
    def _serve_one(self, req):
        t0 = time.perf_counter()
        if req.on_attempt is not None:
            try:
                req.on_attempt(_FakeAttempt(3), None)
            except Exception:
                pass
        v = int(req.arrays.num_vertices)
        return ServeResult(
            request_id=req.request_id, status="ok",
            colors=np.arange(v, dtype=np.int32) % 3, minimal_colors=3,
            attempts=[(3, "SUCCESS", 5)], queue_s=t0 - req.t_submit,
            service_s=time.perf_counter() - t0,
            batched=False, shape_class=None)


_SPEC = {"node_count": 24, "max_degree": 3, "seed": 5,
         "gen_method": "fast"}


def _post(port, path, doc, headers=None):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {}), dict(e.headers)


def _get(port, path):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _poll(port, ticket, timeout=30.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        st, body = _get(port, f"/v1/result/{ticket}?colors=1")
        if st != 202:
            return st, json.loads(body)
        time.sleep(0.01)
    raise TimeoutError(f"ticket {ticket} never terminal")


def _stack(tmp_path, logger=None, **nf_kw):
    front = _InstantFront(batch_max=2, workers=2, queue_depth=32,
                          window_s=0.0, logger=logger).start()
    nf = NetFront(front, logger=logger,
                  journal_dir=str(tmp_path / "journal"), **nf_kw).start()
    return front, nf


# -- W3C traceparent parse/format ---------------------------------------

def test_traceparent_parse_format_roundtrip():
    assert parse_traceparent(TRACEPARENT) == (TRACE_ID, PARENT_ID)
    # case-insensitive, whitespace-tolerant
    assert parse_traceparent(f"  {TRACEPARENT.upper()} ") \
        == (TRACE_ID, PARENT_ID)
    assert format_traceparent(TRACE_ID, PARENT_ID) == TRACEPARENT
    assert format_traceparent(TRACE_ID, PARENT_ID, sampled=False) \
        == f"00-{TRACE_ID}-{PARENT_ID}-00"
    assert parse_traceparent(format_traceparent(TRACE_ID, PARENT_ID)) \
        == (TRACE_ID, PARENT_ID)


@pytest.mark.parametrize("bad", [
    None, 7, "", "garbage",
    f"ff-{TRACE_ID}-{PARENT_ID}-01",          # forbidden version
    f"00-{'0' * 32}-{PARENT_ID}-01",          # all-zero trace id
    f"00-{TRACE_ID}-{'0' * 16}-01",           # all-zero parent id
    f"00-{TRACE_ID[:-1]}-{PARENT_ID}-01",     # short trace id
    f"00-{TRACE_ID}-{PARENT_ID}",             # missing flags
])
def test_traceparent_rejects_invalid(bad):
    assert parse_traceparent(bad) is None


def test_boundary_span_id_is_stable_16hex():
    a = boundary_span_id("t00000007")
    assert a == boundary_span_id("t00000007")
    assert len(a) == 16 and int(a, 16) != 0
    assert a != boundary_span_id("t00000008")


# -- cross-boundary propagation over HTTP -------------------------------

def test_inbound_traceparent_roots_span_tree_and_echoes(tmp_path):
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger)
    try:
        st, doc, headers = _post(nf.port, "/v1/color", dict(_SPEC),
                                 headers={"traceparent": TRACEPARENT})
        assert st == 202
        ticket = doc["ticket"]
        # the 202 continues the trace: caller's id in body AND header,
        # our boundary span id (deterministic per ticket) as parent
        assert doc["trace"] == TRACE_ID
        assert headers["traceparent"] == format_traceparent(
            TRACE_ID, boundary_span_id(ticket))
        st, res = _poll(nf.port, ticket)
        assert st == 200 and res["status"] == "ok"
    finally:
        nf.close()
        front.shutdown()
        logger.close()
    recs = [json.loads(ln) for ln in open(log) if ln.strip()]
    spans = [r for r in recs if r.get("event") == "span"]
    assert spans and all(s["trace"] == TRACE_ID for s in spans)
    # root span records the caller's span id as remote_parent (attrs,
    # not the structural parent the validator would demand a B for)
    roots = [s for s in spans
             if s["name"] == "request" and s["ph"] == "B"]
    assert len(roots) == 1
    assert roots[0]["parent"] is None
    assert roots[0]["attrs"]["remote_parent"] == PARENT_ID
    # the admitted journal record persists the trace context
    ent = scan_journal(str(tmp_path / "journal"
                           / "ticket_journal.jsonl")).tickets[0]
    assert ent.trace == TRACE_ID and ent.trace_parent == PARENT_ID
    # net_admit carries the trace id; the whole log schema-validates
    admits = [r for r in recs if r.get("event") == "net_admit"]
    assert admits and admits[0]["trace"] == TRACE_ID
    assert validate_file(str(log)) == []


def test_no_traceparent_keeps_stream_byte_identical_shape(tmp_path):
    """Flags-unset contract: without the header there is no ``trace``
    field anywhere — not in the 202 body, not in net_admit, not in the
    journal — and spans run under the classic req-<id> trace."""
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger)
    try:
        st, doc, headers = _post(nf.port, "/v1/color", dict(_SPEC))
        assert st == 202
        assert "trace" not in doc
        assert "traceparent" not in {k.lower() for k in headers}
        _poll(nf.port, doc["ticket"])
    finally:
        nf.close()
        front.shutdown()
        logger.close()
    recs = [json.loads(ln) for ln in open(log) if ln.strip()]
    admits = [r for r in recs if r.get("event") == "net_admit"]
    assert admits and "trace" not in admits[0]
    spans = [r for r in recs if r.get("event") == "span"]
    assert spans and all(s["trace"].startswith("req-") for s in spans)
    ent = scan_journal(str(tmp_path / "journal"
                           / "ticket_journal.jsonl")).tickets[0]
    assert ent.trace is None and ent.trace_parent is None


def test_replay_resumes_original_trace_across_incarnations(tmp_path):
    """A ticket journaled with a W3C trace context and crashed in
    flight is replayed under the ORIGINAL trace id with the caller's
    span id re-attached — incarnation 2's spans join incarnation 1's
    trace."""
    j = TicketJournal(str(tmp_path / "journal"))
    j.append("admitted", "t00000007", tenant="x", priority=0,
             payload=dict(_SPEC), trace=TRACE_ID, trace_parent=PARENT_ID)
    j.append("seated", "t00000007")
    j.close()
    log = tmp_path / "incarnation2.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger)
    try:
        st, doc = _poll(nf.port, "t00000007")
        assert st == 200 and doc["status"] == "ok"
    finally:
        nf.close()
        front.shutdown()
        logger.close()
    spans = [json.loads(ln) for ln in open(log)
             if '"span"' in ln and ln.strip()]
    spans = [s for s in spans if s.get("event") == "span"]
    assert spans and all(s["trace"] == TRACE_ID for s in spans)
    roots = [s for s in spans
             if s["name"] == "request" and s["ph"] == "B"]
    assert roots[0]["attrs"]["remote_parent"] == PARENT_ID
    assert validate_file(str(log)) == []


def test_merged_export_one_track_across_incarnations(tmp_path):
    """tools/export_trace.py multi-log merge: two incarnations' spans
    under one trace id land on ONE process track with one thread lane
    per incarnation."""
    from tools.export_trace import merge_chrome_traces, read_spans

    for i, name in enumerate(("inc1.jsonl", "inc2.jsonl")):
        logger = RunLogger(jsonl_path=str(tmp_path / name), echo=False)
        logger.event("span", name="request", ph="B", trace=TRACE_ID,
                     span="s1", parent=None, ts_us=10 + i * 100,
                     attrs=None)
        logger.event("span", name="request", ph="E", trace=TRACE_ID,
                     span="s1", parent=None, ts_us=50 + i * 100,
                     attrs=None)
        logger.close()
    labeled = [(name, read_spans(str(tmp_path / name)))
               for name in ("inc1.jsonl", "inc2.jsonl")]
    doc = merge_chrome_traces(labeled)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    assert len({e["pid"] for e in xs}) == 1     # one track
    assert {e["tid"] for e in xs} == {1, 2}     # two incarnation lanes
    assert {e["args"]["source"] for e in xs} \
        == {"inc1.jsonl", "inc2.jsonl"}
    names = [e for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert {m["args"]["name"] for m in names} \
        == {"inc1.jsonl", "inc2.jsonl"}


# -- per-tenant usage metering ------------------------------------------

def test_usage_meter_lifecycle_and_device_attribution():
    m = UsageMeter()
    m.record_admitted("acme", 100, trace=TRACE_ID)
    m.record_admitted("acme", 50)
    m.record_admitted("bob", 10)
    m.record_done("acme", "ok", 0.5, 1.5, vertices=100, supersteps=7)
    m.record_done("bob", "error", 0.1, 0.2)
    m.record_aborted("acme")
    # the RunLogger-sink half: closing sweep spans charge device time
    # to the tenant whose trace was bound at admission
    m({"event": "span", "ph": "E", "trace": TRACE_ID,
       "attrs": {"device_us": 2500}})
    m({"event": "span", "ph": "E", "trace": "unknown-trace",
       "attrs": {"device_us": 999}})           # unbound: dropped
    m({"event": "span", "ph": "E", "trace": TRACE_ID,
       "attrs": {"device_us": True}})          # bool is not device time
    rows = {r["tenant"]: r for r in m.snapshot()}
    acme = rows["acme"]
    assert acme["admitted"] == 2 and acme["delivered"] == 1
    assert acme["aborted"] == 1 and acme["in_flight"] == 0
    assert acme["vertices"] == 150
    assert acme["vertex_supersteps"] == 700
    assert acme["device_ms"] == 2.5
    assert acme["queue_ms"] == 500.0 and acme["service_ms"] == 1500.0
    assert acme["source"] == "live" and acme["export_version"] == 1
    bob = rows["bob"]
    assert bob["failed"] == 1 and bob["delivered"] == 0
    assert payload_vertices(dict(_SPEC)) == 24
    assert payload_vertices({"graph": [[1], [0]]}) == 2
    assert payload_vertices("junk") == 0


def test_admin_usage_route_live_rows(tmp_path):
    logger = RunLogger(echo=False)
    front, nf = _stack(tmp_path, logger=logger)
    try:
        st, doc, _ = _post(nf.port, "/v1/color", dict(_SPEC))
        assert st == 202
        _poll(nf.port, doc["ticket"])
        st, body = _get(nf.port, "/admin/usage")
        assert st == 200
        rows = json.loads(body)["usage"]
        assert len(rows) == 1
        row = rows[0]
        assert row["tenant"] == "anon" and row["admitted"] == 1
        assert row["delivered"] == 1 and row["in_flight"] == 0
        assert row["vertices"] == _SPEC["node_count"]
        assert row["source"] == "live"
    finally:
        nf.close()
        front.shutdown()


def test_journal_fold_conservation_exact(tmp_path):
    """fold_journal over a multi-tenant journal with crash-duplicate
    records: per-tenant sums EXACTLY equal journal_totals, and a
    deliberately broken fold is caught."""
    j = TicketJournal(str(tmp_path))
    j.append("admitted", "t00000000", tenant="a", payload=dict(_SPEC))
    # crash-window duplicate admit of the same ticket: metered once
    j.append("admitted", "t00000000", tenant="a", payload=dict(_SPEC))
    j.append("attempt", "t00000000", durable=False, k=3,
             status="SUCCESS", supersteps=5)
    j.append("delivered", "t00000000", durable=False,
             result={"status": "ok", "queue_ms": 2.0, "service_ms": 8.0})
    j.append("admitted", "t00000001", tenant="b", payload=dict(_SPEC))
    j.append("aborted", "t00000001", reason="queue_full")
    j.append("admitted", "t00000002", tenant="b", payload=dict(_SPEC))
    j.append("failed", "t00000002", durable=False,
             result={"status": "error", "error": "rc 114"})
    j.append("admitted", "t00000003", tenant="a", payload=dict(_SPEC))
    j.close()
    rows = fold_journal(j.path)
    assert [r["tenant"] for r in rows] == ["a", "b"]
    a, b = rows
    assert a["admitted"] == 2 and a["delivered"] == 1
    assert a["in_flight"] == 1                     # t3 never finished
    assert a["vertex_supersteps"] == 24 * 5
    assert a["queue_ms"] == 2.0 and a["service_ms"] == 8.0
    assert b["admitted"] == 2 and b["aborted"] == 1 and b["failed"] == 1
    assert a["source"] == "journal"
    totals = journal_totals(j.path)
    assert totals == {"admitted": 4, "delivered": 1, "failed": 1,
                      "aborted": 1, "cached": 0, "vertices": 96}
    assert conservation_problems(rows, j.path) == []
    # a lost ticket or a double-metered terminal does NOT conserve
    broken = [dict(r) for r in rows]
    broken[0]["delivered"] += 1
    assert any("delivered" in p
               for p in conservation_problems(broken, j.path))
    broken[0]["delivered"] -= 2
    probs = conservation_problems(broken, j.path)
    assert any("delivered" in p for p in probs)


def test_usage_export_cli_artifact_and_check(tmp_path, capsys):
    from tools.usage_export import main as export_main

    jdir = tmp_path / "journal"
    j = TicketJournal(str(jdir))
    j.append("admitted", "t00000000", tenant="acme",
             payload=dict(_SPEC), trace=TRACE_ID)
    j.append("delivered", "t00000000", durable=False,
             result={"status": "ok"})
    j.close()
    # a run log supplies the device-time column through the trace join
    log = tmp_path / "server_0.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    logger.event("span", name="sweep", ph="E", trace=TRACE_ID,
                 span="s2", parent=None, ts_us=9, attrs={"device_us": 4000})
    logger.close()
    out = tmp_path / "usage.jsonl"
    rc = export_main([str(jdir), "--logs", str(log), "-o", str(out),
                      "--check"])
    assert rc == 0
    lines = [json.loads(ln) for ln in open(out) if ln.strip()]
    assert len(lines) == 1
    assert lines[0]["event"] == "usage_rollup"
    assert lines[0]["tenant"] == "acme"
    assert lines[0]["device_ms"] == 4.0
    # the artifact is a schema-valid run log
    assert validate_file(str(out)) == []
    err = capsys.readouterr().err
    assert "conservation" in err
    # missing journal is a structured error, not a traceback
    assert export_main([str(tmp_path / "nope")]) == 2


# -- timeseries sampler + burn-rate evaluator ---------------------------

def test_sampler_ring_bounded_and_routes(tmp_path):
    registry = MetricsRegistry()
    counter = registry.counter("dgc_demo_total", "demo")
    sampler = TimeseriesSampler(registry, interval_s=9.0, capacity=4)
    for i in range(7):
        counter.inc()
        sampler.sample_once()
    snap = sampler.snapshot()
    assert len(snap) == 4                       # ring bound
    assert snap[-1]["metrics"]["dgc_demo_total"]["value"] == 7.0
    assert snap[0]["metrics"]["dgc_demo_total"]["value"] == 4.0
    assert snap[0]["mono"] <= snap[-1]["mono"]
    dump = tmp_path / "ts.jsonl"
    assert sampler.write_jsonl(str(dump)) == 4
    assert len([ln for ln in open(dump) if ln.strip()]) == 4
    with pytest.raises(ValueError):
        TimeseriesSampler(registry, interval_s=0.0)
    # the listener serves the ring live at /debug/timeseries
    front = _InstantFront(batch_max=1, workers=1, queue_depth=8,
                          window_s=0.0).start()
    nf = NetFront(front, timeseries=sampler).start()
    try:
        st, body = _get(nf.port, "/debug/timeseries")
        assert st == 200
        served = [json.loads(ln) for ln in body.decode().splitlines()
                  if ln.strip()]
        assert len(served) == 4
        assert served[-1]["metrics"]["dgc_demo_total"]["value"] == 7.0
    finally:
        nf.close()
        front.shutdown()
    sampler.close()


def test_burn_evaluator_fires_on_sustained_burn(tmp_path):
    """Failure-rate burn over both windows fires slo_burn, bumps the
    counter, dumps the flight recorder, and cools down."""
    import sys

    sys.path.insert(0, "tools")
    import slo_check

    registry = MetricsRegistry()
    log = tmp_path / "burn.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    recorder = FlightRecorder(capacity=32, registry=registry)
    logger.add_sink(recorder)
    hooks = slo_check.ViolationHooks(recorder=recorder,
                                     dump_dir=str(tmp_path),
                                     logger=logger)
    sampler = TimeseriesSampler(registry, interval_s=9.0, capacity=16)
    ev = BurnRateEvaluator(sampler, {"failure_rate_max": 0.1},
                           fast_window_s=0.1, slow_window_s=0.1,
                           hooks=hooks, logger=logger, registry=registry)
    ok = registry.counter("dgc_serve_requests_total", "reqs", status="ok")
    err = registry.counter("dgc_serve_requests_total", "reqs",
                           status="error")
    ok.inc()
    sampler.sample_once()
    # a warmed window (>= half its span of coverage) full of failures
    time.sleep(0.06)
    for _ in range(9):
        err.inc()
    sample = sampler.sample_once()
    fired = ev.evaluate(sample)
    assert [f["objective"] for f in fired] == ["failure_rate"]
    assert fired[0]["slow_burn"] == pytest.approx(10.0, rel=1e-3)
    assert ev.fired == 1
    # cooldown (= fast window) suppresses an immediate re-fire
    assert ev.evaluate(sampler.sample_once()) == []
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if ln.strip()]
    burns = [r for r in recs if r.get("event") == "slo_burn"]
    assert len(burns) == 1
    b = burns[0]
    assert b["objective"] == "failure_rate" and b["burn"] >= 1.0
    assert b["limit"] == 0.1 and b["profile"] is False
    # the hook dumped the recorder while the incident was live
    assert b["dump"] and (tmp_path / b["dump"].split("/")[-1]).exists()
    dumps = [r for r in recs if r.get("event") == "flightrec_dump"]
    assert dumps and dumps[0]["reason"] == "slo_violation"
    key = 'dgc_slo_burn_fired_total{objective="failure_rate"}'
    assert registry.to_dict()[key]["value"] == 1.0
    assert validate_file(str(log)) == []


def test_burn_evaluator_quiet_without_traffic_or_warmup():
    registry = MetricsRegistry()
    sampler = TimeseriesSampler(registry, interval_s=9.0, capacity=16)
    ev = BurnRateEvaluator(sampler, {"failure_rate_max": 0.0,
                                     "service_ms": {"p95": 50}},
                           fast_window_s=0.05, slow_window_s=0.05)
    assert ev.evaluate() == []                  # empty ring
    sampler.sample_once()
    assert ev.evaluate() == []                  # single sample: no base
    time.sleep(0.04)
    # no traffic in the window -> no evidence -> no burn, even with a
    # zero-tolerance failure objective
    assert ev.evaluate(sampler.sample_once()) == []
    with pytest.raises(ValueError):
        BurnRateEvaluator(sampler, {}, fast_window_s=10, slow_window_s=1)


def test_burn_evaluator_latency_quantile_objective():
    registry = MetricsRegistry()
    sampler = TimeseriesSampler(registry, interval_s=9.0, capacity=16)
    ev = BurnRateEvaluator(sampler, {"service_ms": {"p95": 10.0}},
                           fast_window_s=0.05, slow_window_s=0.05,
                           registry=registry)
    hist = registry.histogram("dgc_serve_service_seconds", "svc",
                              shape_class="c128")
    sampler.sample_once()
    time.sleep(0.04)
    for _ in range(20):
        hist.observe(0.5)                       # 500 ms >> 10 ms limit
    fired = ev.evaluate(sampler.sample_once())
    assert [f["objective"] for f in fired] == ["service_ms_p95"]
    assert fired[0]["value"] > 10.0


# -- flight recorder: mesh_degrade auto-dump ----------------------------

def test_flightrec_auto_dump_on_mesh_degrade(tmp_path):
    log = tmp_path / "mesh.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    recorder = FlightRecorder(capacity=32)
    logger.add_sink(recorder)
    recorder.arm_auto_dump({"mesh_degrade"}, str(tmp_path),
                           logger=logger, cooldown_s=60.0)
    logger.event("mesh_restore", devices_before=7, devices_after=8)
    assert not list(tmp_path.glob("flightrec_*.jsonl"))
    logger.event("mesh_degrade", devices_before=8, devices_after=7,
                 lost_device=3, reseated=2, quarantined=1)
    dumps = list(tmp_path.glob("flightrec_*.jsonl"))
    assert len(dumps) == 1
    dumped = [json.loads(ln) for ln in open(dumps[0]) if ln.strip()]
    assert any(r.get("event") == "mesh_degrade" for r in dumped)
    meta = [r for r in dumped if r.get("event") == "flightrec_dump"]
    assert meta and meta[0]["reason"] == "auto"
    assert meta[0]["trigger"] == "mesh_degrade"
    # cooldown: a second degrade inside the window does not re-dump
    logger.event("mesh_degrade", devices_before=7, devices_after=6)
    assert len(list(tmp_path.glob("flightrec_*.jsonl"))) == 1
    logger.close()
    # arming the dump's own event kind would recurse: rejected
    with pytest.raises(ValueError):
        recorder.arm_auto_dump({"flightrec_dump"}, str(tmp_path))
    assert validate_file(str(log)) == []


# -- schema + validate_runlog semantics ---------------------------------

def _write_log(tmp_path, records):
    path = tmp_path / "log.jsonl"
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps({"t": 0.1, **rec}) + "\n")
    return str(path)


def _usage_rec(**over):
    rec = {"event": "usage_rollup", "tenant": "a", "admitted": 2,
           "delivered": 1, "failed": 0, "aborted": 0, "in_flight": 1,
           "vertices": 48, "vertex_supersteps": 120, "device_ms": 1.5,
           "queue_ms": 2.0, "service_ms": 9.0, "source": "journal",
           "export_version": 1}
    rec.update(over)
    return rec


def _burn_rec(**over):
    rec = {"event": "slo_burn", "objective": "failure_rate",
           "window_s": 300.0, "burn": 4.2, "fast_window_s": 60.0,
           "slow_window_s": 300.0, "fast_burn": 5.0, "slow_burn": 4.2,
           "threshold": 1.0, "value": 0.42, "limit": 0.1, "dump": None,
           "profile": False}
    rec.update(over)
    return rec


def test_usage_rollup_schema_and_semantics(tmp_path):
    assert validate_file(_write_log(tmp_path, [_usage_rec()])) == []
    for bad in (_usage_rec(admitted=-1),
                _usage_rec(in_flight=-2),
                _usage_rec(source="billing"),
                {k: v for k, v in _usage_rec().items() if k != "tenant"}):
        assert validate_file(_write_log(tmp_path, [bad])) != []


def test_slo_burn_schema_and_semantics(tmp_path):
    assert validate_file(_write_log(tmp_path, [_burn_rec()])) == []
    assert validate_file(_write_log(
        tmp_path, [_burn_rec(objective="service_ms_p99",
                             dump="flightrec_1.jsonl")])) == []
    for bad in (_burn_rec(window_s=0),
                _burn_rec(burn=-1.0),
                _burn_rec(objective="vibes"),
                {k: v for k, v in _burn_rec().items() if k != "burn"}):
        assert validate_file(_write_log(tmp_path, [bad])) != []

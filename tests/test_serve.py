"""Serving-path tests (dgc_tpu.serve): shape classes, batched parity,
queue semantics, health, CLI subcommand. Tier-1 fast under
``JAX_PLATFORMS=cpu`` with the ``serve`` marker; the 1k-request soak is
``slow``."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dgc_tpu.engine.compact import CompactFrontierEngine
from dgc_tpu.engine.minimal_k import (find_minimal_coloring, make_reducer,
                                      make_validator)
from dgc_tpu.models.generators import (generate_random_graph,
                                       generate_random_graph_fast,
                                       generate_rmat_graph)
from dgc_tpu.serve.engine import BatchMemberEngine, BatchScheduler
from dgc_tpu.serve.queue import QueueFull, ServeFrontEnd
from dgc_tpu.serve.shape_classes import (DEFAULT_LADDER, ShapeClass,
                                         ShapeLadder, dummy_member,
                                         pad_member)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _single_graph_reference(g):
    """The parity target: the single-graph fused jump-mode sweep with the
    CLI defaults (validate + recolor pass)."""
    attempts = []
    res = find_minimal_coloring(
        CompactFrontierEngine(g), initial_k=g.max_degree + 1,
        validate=make_validator(g),
        on_attempt=lambda r, v: attempts.append(
            (int(r.k), r.status.name, int(r.supersteps))),
        post_reduce=make_reducer(g))
    return res, attempts


# -- shape classes ------------------------------------------------------

def test_shape_class_selection():
    ladder = DEFAULT_LADDER
    cls = ladder.class_for(1500, 19)
    assert (cls.v_pad, cls.w_pad) == (2048, 32)
    assert ladder.class_for(2049, 19).v_pad == 8192
    assert ladder.class_for(1500, 33).w_pad == 64
    # beyond the ladder: single-graph fallback
    assert ladder.class_for(10**7, 10) is None
    assert ladder.class_for(100, 5000) is None
    # every class window covers its width (the bit-identity precondition)
    for c in ladder.classes():
        assert 32 * c.planes >= c.w_pad + 1


def test_shape_ladder_validation():
    with pytest.raises(ValueError):
        ShapeLadder(v_rungs=(), w_rungs=(8,))
    with pytest.raises(ValueError):
        ShapeLadder(v_rungs=(1024, 512), w_rungs=(8,))
    with pytest.raises(ValueError):   # width rung needing > 32 planes
        ShapeLadder(v_rungs=(1024,), w_rungs=(2048,))


def test_pad_ladder():
    from dgc_tpu.serve.shape_classes import pad_ladder

    assert pad_ladder(8) == (8, 4, 2, 1)
    # non-pow2 batch_max: sync full batches dispatch at batch_max itself
    assert pad_ladder(6) == (8, 6, 4, 2, 1)
    assert pad_ladder(1) == (1,)


def test_pad_member_invariants():
    g = generate_random_graph(60, 6, seed=0)
    cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
    m = pad_member(g.arrays if hasattr(g, "arrays") else g, cls)
    assert m.comb.shape == (cls.v_pad, cls.w_pad)
    assert m.degrees.shape == (cls.v_pad,)
    v = m.num_vertices
    assert (m.degrees[v:] == 0).all()
    # pad rows are all-sentinel (no real row points at them either)
    nbr = m.comb & ((1 << 30) - 1)
    assert (nbr[v:] == cls.v_pad).all()
    assert (nbr[(nbr < cls.v_pad)] < v).all()
    assert m.k0 == int(np.max(m.degrees)) + 1
    assert m.max_steps == 2 * v + 4
    with pytest.raises(ValueError):
        pad_member(g.arrays if hasattr(g, "arrays") else g,
                   ShapeClass(32, 2))


# -- batched sweeps: bit-identity with the single-graph fused engine ----

def test_batched_minimal_k_matches_single_graph():
    sched = BatchScheduler(batch_max=4, window_s=0.01).start()
    try:
        for seed, gen in [(0, "uniform"), (1, "rmat"), (2, "uniform"),
                          (3, "rmat")]:
            g = (generate_random_graph_fast(700, avg_degree=8, seed=seed)
                 if gen == "uniform"
                 else generate_rmat_graph(700, avg_degree=8, seed=seed))
            cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
            engine = BatchMemberEngine(pad_member(g, cls), sched)
            got_attempts = []
            got = find_minimal_coloring(
                engine, initial_k=engine.member.k0,
                validate=make_validator(g),
                on_attempt=lambda r, v: got_attempts.append(
                    (int(r.k), r.status.name, int(r.supersteps))),
                post_reduce=make_reducer(g))
            want, want_attempts = _single_graph_reference(g)
            assert got.minimal_colors == want.minimal_colors
            assert np.array_equal(got.colors, want.colors)
            assert got_attempts == want_attempts
    finally:
        sched.stop()


def test_batch_composition_invariance():
    """The same graph colored alone, and inside batches of different
    company/position, yields byte-identical output."""
    g = generate_random_graph_fast(900, avg_degree=8, seed=7)
    others = [generate_random_graph_fast(500 + 100 * i, avg_degree=6,
                                         seed=20 + i) for i in range(3)]

    def run_batch(graphs):
        fe = ServeFrontEnd(batch_max=4, window_s=0.05,
                           queue_depth=16).start()
        try:
            tickets = [fe.submit(x) for x in graphs]
            return [t.result(timeout=300) for t in tickets]
        finally:
            fe.shutdown()

    alone = run_batch([g])[0]
    first = run_batch([g] + others)[0]
    last = run_batch(others + [g])[-1]
    for r in (alone, first, last):
        assert r.ok
        assert r.minimal_colors == alone.minimal_colors
        assert np.array_equal(r.colors, alone.colors)
        assert r.attempts == alone.attempts


def test_dummy_member_is_inert():
    cls = ShapeClass(2048, 8)
    m = dummy_member(cls)
    assert m.k0 == 1 and (m.degrees == 0).all()
    # a dummy co-member never perturbs a real graph's result: batch of 1
    # real graph pads with dummies internally (b_pad rounding)
    g = generate_random_graph_fast(600, avg_degree=6, seed=3)
    sched = BatchScheduler(batch_max=8, window_s=0.0).start()
    try:
        engine = BatchMemberEngine(
            pad_member(g, DEFAULT_LADDER.class_for(g.num_vertices,
                                                   g.max_degree)), sched)
        got = find_minimal_coloring(engine, initial_k=engine.member.k0)
    finally:
        sched.stop()
    want, _ = _single_graph_reference(g)
    # compare the swept count (got ran without the recolor post-pass)
    assert got.minimal_colors == want.swept_colors


def test_compile_cache_hits_on_recurring_shapes():
    sched = BatchScheduler(batch_max=2, window_s=0.0).start()
    try:
        for seed in range(3):
            g = generate_random_graph_fast(800, avg_degree=8, seed=seed)
            cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
            engine = BatchMemberEngine(pad_member(g, cls), sched)
            find_minimal_coloring(engine, initial_k=engine.member.k0)
    finally:
        sched.stop()
    assert sched.stats["compile_misses"] >= 1
    # recurring shape: later sweeps reuse the class kernel
    assert sched.stats["compile_hits"] > sched.stats["compile_misses"]


# -- lane recycling (continuous batching) -------------------------------

def test_slice_kernel_bit_identical_to_sweep_kernel():
    """The sliced kernel re-entered to completion equals the unsliced
    kernel byte for byte, for every slice size — the chunked while-loop
    re-entry is result-invariant however the budget partitions the
    sweep (shared ``_superstep_body``)."""
    import numpy as np

    from dgc_tpu.layout import CARRY_PHASE, OUT0
    from dgc_tpu.serve.batched import (batched_slice_kernel,
                                       batched_sweep_kernel, idle_carry)

    cls = ShapeClass(2048, 32)
    graphs = [generate_random_graph_fast(700, avg_degree=8, seed=s)
              for s in range(3)]
    members = [pad_member(g, cls) for g in graphs] + [dummy_member(cls)]
    comb = np.stack([m.comb for m in members])
    degrees = np.stack([m.degrees for m in members])
    k0 = np.array([m.k0 for m in members], np.int32)
    max_steps = np.array([m.max_steps for m in members], np.int32)

    want = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes)]

    for s in (1, 3, 7):
        carry = idle_carry(4, cls.v_pad)
        reset = np.ones(4, np.int32)
        for _ in range(1000):
            carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                         reset, carry, planes=cls.planes,
                                         slice_steps=s)
            reset = np.zeros(4, np.int32)
            if (np.asarray(carry[CARRY_PHASE]) >= 2).all():
                break
        else:
            raise AssertionError("slice loop did not converge")
        got = [np.asarray(a) for a in carry[OUT0:]]
        for g_arr, w_arr in zip(got, want):
            assert np.array_equal(g_arr, w_arr), f"slice_steps={s}"


def test_slice_kernel_timing_variant_bit_identical():
    """The timing-compiled slice kernel (in-kernel clock accumulating
    per-lane superstep µs into the carry's timing slots) returns result
    slots byte-identical to the untimed kernel, and real lanes
    accumulate positive device time."""
    import numpy as np

    from dgc_tpu.layout import CARRY_PHASE, N_OUT, OUT0, T_US
    from dgc_tpu.serve.batched import (batched_slice_kernel,
                                       batched_sweep_kernel, idle_carry)

    cls = ShapeClass(2048, 32)
    graphs = [generate_random_graph_fast(700, avg_degree=8, seed=s)
              for s in range(3)]
    members = [pad_member(g, cls) for g in graphs] + [dummy_member(cls)]
    comb = np.stack([m.comb for m in members])
    degrees = np.stack([m.degrees for m in members])
    k0 = np.array([m.k0 for m in members], np.int32)
    max_steps = np.array([m.max_steps for m in members], np.int32)

    want = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes)]

    carry = idle_carry(4, cls.v_pad)
    reset = np.ones(4, np.int32)
    for _ in range(1000):
        carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                     reset, carry, planes=cls.planes,
                                     slice_steps=3, timing=True)
        reset = np.zeros(4, np.int32)
        if (np.asarray(carry[CARRY_PHASE]) >= 2).all():
            break
    else:
        raise AssertionError("timed slice loop did not converge")
    got = [np.asarray(a) for a in carry[OUT0:OUT0 + N_OUT]]
    for g_arr, w_arr in zip(got, want):
        assert np.array_equal(g_arr, w_arr)
    t_us = np.asarray(carry[T_US])
    assert (t_us[:3] > 0).all()        # real lanes accumulated device µs
    assert (t_us >= 0).all()


def test_priced_slice_steps_and_measured_recalibration():
    """The slice-size pricing rule on measured numbers, and the
    scheduler's once-per-class recalibration: after recal_min_slices
    full timed slices, resolved_slice_steps freezes to the re-priced
    value and a slice_recalibrated event is emitted."""
    from dgc_tpu.serve.batched import auto_slice_steps, priced_slice_steps
    from dgc_tpu.serve.engine import BatchScheduler

    # the pricing rule itself: overhead ≤ 1/8 of slice compute, clamped
    assert priced_slice_steps(0.064, 0.008) == 64
    assert priced_slice_steps(0.0001, 0.1) == 4        # lo clamp
    assert priced_slice_steps(10.0, 0.001) == 64       # hi clamp
    assert priced_slice_steps(0.01, 0.004) == 20
    # auto_slice_steps delegates to it (model-fed)
    assert auto_slice_steps(10_000, 1, "cpu") >= 4

    cls = ShapeClass(2048, 32)
    events = []
    sched = BatchScheduler(timing=True, slice_steps=None,
                           recal_min_slices=3,
                           on_event=lambda k, r: events.append((k, r)))
    s0 = sched.resolved_slice_steps(cls, 1)
    # feed three measured (overhead, per-superstep) samples whose priced
    # size differs from the model's
    for _ in range(3):
        sched._timing_sample(cls, overhead_s=0.050, iter_s=0.001)
    s1 = sched.resolved_slice_steps(cls, 1)
    assert s1 == priced_slice_steps(0.050, 0.001)
    assert s1 != s0 or sched.stats["recals"] == 0
    if s1 != s0:
        assert sched.stats["recals"] == 1
        [(kind, rec)] = [e for e in events if e[0] == "slice_recalibrated"]
        assert rec["shape_class"] == cls.name
        assert rec["to_steps"] == s1 and rec["samples"] == 3
    # frozen: more samples never re-price
    for _ in range(10):
        sched._timing_sample(cls, overhead_s=0.001, iter_s=0.1)
    assert sched.resolved_slice_steps(cls, 1) == s1
    # an explicit slice_steps is never overridden
    sched2 = BatchScheduler(timing=True, slice_steps=5, recal_min_slices=1)
    sched2._timing_sample(cls, overhead_s=0.050, iter_s=0.001)
    assert sched2.resolved_slice_steps(cls, 4) == 5


def _serve_all(graphs, telemetry: bool, **fe_kwargs):
    logger = None
    if telemetry:
        import io

        from dgc_tpu.obs import RunLogger

        logger = RunLogger(stream=io.StringIO(), echo=False)
    fe = ServeFrontEnd(logger=logger, **fe_kwargs).start()
    try:
        tickets = [fe.submit(g) for g in graphs]
        return [t.result(timeout=600) for t in tickets], fe.scheduler.stats
    finally:
        fe.shutdown()


def test_recycling_parity_mixed_depth_batches():
    """Mixed-depth batches with lanes recycling mid-sweep (more requests
    than lanes, slice_steps=2 so every sweep crosses many recycling
    boundaries): per-graph colors / minimal-k / attempt sequences stay
    byte-identical to ``CompactFrontierEngine.sweep``, telemetry on and
    off."""
    # same v2048 class, very different predicted depths (k0 ~ 6 vs ~30+)
    graphs = []
    for i in range(6):
        deep = i % 2
        graphs.append(generate_random_graph_fast(
            500 + 150 * i, avg_degree=(20 if deep else 5), seed=40 + i))
    kw = dict(batch_max=3, window_s=0.05, queue_depth=16, slice_steps=2)
    with_t, stats = _serve_all(graphs, telemetry=True, **kw)
    without_t, _ = _serve_all(graphs, telemetry=False, **kw)
    assert stats["recycles"] >= 6      # lanes actually recycled
    assert stats["slices"] > stats["recycles"]  # mid-sweep boundaries
    for g, r_t, r_p in zip(graphs, with_t, without_t):
        want, want_attempts = _single_graph_reference(g)
        for r in (r_t, r_p):
            assert r.ok
            assert r.minimal_colors == want.minimal_colors
            assert np.array_equal(r.colors, want.colors)
            assert [tuple(a) for a in r.attempts] == want_attempts


def test_lane_recycled_at_attempt_boundary():
    """slice_steps=1 makes EVERY superstep a recycling boundary —
    including the minimal-k attempt boundary inside the jump pair (the
    phase 0 → 1 transition) — while a second request wave swaps into
    lanes freed mid-flight. Results stay byte-identical per graph."""
    graphs = [generate_random_graph_fast(400 + 100 * i, avg_degree=6,
                                         seed=60 + i) for i in range(5)]
    results, stats = _serve_all(graphs, telemetry=False, batch_max=2,
                                window_s=0.05, queue_depth=16,
                                slice_steps=1)
    assert stats["recycles"] >= 5
    for g, r in zip(graphs, results):
        want, want_attempts = _single_graph_reference(g)
        assert r.ok and r.minimal_colors == want.minimal_colors
        assert np.array_equal(r.colors, want.colors)
        assert [tuple(a) for a in r.attempts] == want_attempts


def test_three_slice_recycled_batch_end_to_end():
    """Fast tier-1 recycling path: a batch whose sweeps span >= 3 slices
    end-to-end, every sweep delivered through a lane recycle."""
    graphs = [generate_random_graph_fast(300, avg_degree=5, seed=s)
              for s in range(3)]
    results, stats = _serve_all(graphs, telemetry=False, batch_max=3,
                                window_s=0.05, queue_depth=8,
                                slice_steps=3)
    assert all(r.ok for r in results)
    assert stats["slices"] >= 3
    assert stats["recycles"] == stats["sweeps"] >= 3


# -- staged frontier ladder + device-resident carry (PR 9) --------------

# a 3-rung ladder valid for the v2048 test class — small graphs cross
# every stage transition in a handful of supersteps
_TEST_STAGES = ((None, 512), (512, 128), (128, 0))


def _class_batch(cls, n_real=3, seed0=0):
    graphs = [generate_random_graph_fast(700, avg_degree=8, seed=seed0 + s)
              for s in range(n_real)]
    members = [pad_member(g, cls) for g in graphs] + [dummy_member(cls)]
    comb = np.stack([m.comb for m in members])
    degrees = np.stack([m.degrees for m in members])
    k0 = np.array([m.k0 for m in members], np.int32)
    max_steps = np.array([m.max_steps for m in members], np.int32)
    return comb, degrees, k0, max_steps


def test_stage_schedule_resolution():
    """Class ladders come from the single-graph engine's schedule
    machinery: small classes are ladder-free, big classes get
    default_stages' rungs, 'off' and explicit ladders override."""
    from dgc_tpu.engine.compact import serve_stage_rungs
    from dgc_tpu.serve.engine import BatchScheduler
    from dgc_tpu.serve.shape_classes import stage_schedule_for

    assert stage_schedule_for(ShapeClass(2048, 8)) is None
    assert stage_schedule_for(ShapeClass(8192, 32)) is None
    big = ShapeClass(32768, 64)
    assert stage_schedule_for(big) == serve_stage_rungs(32768)
    assert stage_schedule_for(big)[0] == (None, 16384)   # v/2 top rung
    assert stage_schedule_for(big, "off") is None
    assert stage_schedule_for(ShapeClass(2048, 8),
                              _TEST_STAGES) == _TEST_STAGES

    sched = BatchScheduler(stages="off")
    assert sched.stages_for(big) is None
    sched2 = BatchScheduler(stages=_TEST_STAGES)
    assert sched2.stages_for(ShapeClass(2048, 8)) == _TEST_STAGES
    sched3 = BatchScheduler()   # auto
    assert sched3.stages_for(ShapeClass(2048, 8)) is None
    assert sched3.stages_for(big) == serve_stage_rungs(32768)
    with pytest.raises(ValueError):
        BatchScheduler(stages="bogus")
    # malformed explicit ladders fail loudly at kernel build (the
    # engine's _check_stage_ladder rule, shared)
    with pytest.raises(ValueError):
        stage_schedule_for(ShapeClass(2048, 8), ((None, 512), (64, 0)))


def test_staged_sweep_kernel_bit_identical_to_full_table():
    """The staged ladder changes only which rows are gathered: the
    staged batch kernel's outputs equal the full-table kernel's byte for
    byte (colors, steps, statuses, used)."""
    from dgc_tpu.serve.batched import batched_sweep_kernel

    cls = ShapeClass(2048, 32)
    comb, degrees, k0, max_steps = _class_batch(cls)
    want = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes)]
    got = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes,
        stages=_TEST_STAGES)]
    for g_arr, w_arr in zip(got, want):
        assert np.array_equal(g_arr, w_arr)


def test_staged_slice_kernel_stage_boundaries_at_s1():
    """slice_steps=1 makes EVERY superstep a slice re-entry — including
    the supersteps landing exactly on every compaction-stage transition
    and the attempt boundary's rung reset — and the re-entered staged
    kernel still equals the unstaged unsliced kernel byte for byte. The
    rung/nc carry slots actually walk the ladder."""
    from dgc_tpu.layout import CARRY_NC, CARRY_PHASE, CARRY_RUNG, OUT0
    from dgc_tpu.serve.batched import (batched_slice_kernel,
                                       batched_sweep_kernel, idle_carry,
                                       stage_idx_width)

    cls = ShapeClass(2048, 32)
    comb, degrees, k0, max_steps = _class_batch(cls)
    want = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes)]
    carry = idle_carry(4, cls.v_pad, stage_idx_width(_TEST_STAGES))
    reset = np.ones(4, np.int32)
    rungs_seen = set()
    for _ in range(2000):
        carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                     reset, carry, planes=cls.planes,
                                     slice_steps=1, stages=_TEST_STAGES)
        reset = np.zeros(4, np.int32)
        rungs_seen.update(np.asarray(carry[CARRY_RUNG]).tolist())
        nc = np.asarray(carry[CARRY_NC])
        assert (nc >= 0).all() and (nc <= cls.v_pad).all()
        if (np.asarray(carry[CARRY_PHASE]) >= 2).all():
            break
    else:
        raise AssertionError("staged S=1 slice loop did not converge")
    assert {0, 1, 2} <= rungs_seen    # the ladder was actually walked
    got = [np.asarray(a) for a in carry[OUT0:]]
    for g_arr, w_arr in zip(got, want):
        assert np.array_equal(g_arr, w_arr)


def test_reset_lane_reinit_mid_ladder():
    """A lane reset while it sits mid-ladder (rung > 0) re-initializes
    to rung 0 and sweeps its NEW graph bit-identically — and the
    co-resident lanes (dragged back to full-table by the shared
    executed rung) still finish byte-identical to their solo sweeps."""
    from dgc_tpu.layout import CARRY_PHASE, CARRY_RUNG, OUT0, N_OUT
    from dgc_tpu.serve.batched import (batched_slice_kernel,
                                       batched_sweep_kernel, idle_carry,
                                       stage_idx_width)

    cls = ShapeClass(2048, 32)
    comb, degrees, k0, max_steps = _class_batch(cls)
    new_graph = generate_random_graph_fast(900, avg_degree=9, seed=77)
    new_m = pad_member(new_graph, cls)
    want = [np.asarray(o) for o in batched_sweep_kernel(
        comb, degrees, k0, max_steps, planes=cls.planes)]
    want_new = [np.asarray(o) for o in batched_sweep_kernel(
        new_m.comb[None], new_m.degrees[None],
        np.array([new_m.k0], np.int32),
        np.array([new_m.max_steps], np.int32), planes=cls.planes)]

    carry = idle_carry(4, cls.v_pad, stage_idx_width(_TEST_STAGES))
    reset = np.ones(4, np.int32)
    for _ in range(2000):
        carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                     reset, carry, planes=cls.planes,
                                     slice_steps=1, stages=_TEST_STAGES)
        reset = np.zeros(4, np.int32)
        if int(np.asarray(carry[CARRY_RUNG])[0]) > 0:
            break
    else:
        raise AssertionError("lane 0 never climbed the ladder")
    # swap lane 0's inputs for the new graph mid-ladder
    comb[0] = new_m.comb
    degrees[0] = new_m.degrees
    k0[0] = new_m.k0
    max_steps[0] = new_m.max_steps
    reset = np.array([1, 0, 0, 0], np.int32)
    for _ in range(2000):
        carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                     reset, carry, planes=cls.planes,
                                     slice_steps=1, stages=_TEST_STAGES)
        reset = np.zeros(4, np.int32)
        if (np.asarray(carry[CARRY_PHASE]) >= 2).all():
            break
    else:
        raise AssertionError("post-swap slice loop did not converge")
    got = [np.asarray(a) for a in carry[OUT0:OUT0 + N_OUT]]
    for j in range(N_OUT):
        assert np.array_equal(got[j][0], want_new[j][0])   # the new graph
        for lane in (1, 2, 3):                             # co-residents
            assert np.array_equal(got[j][lane], want[j][lane])


def test_staged_timing_variant_byte_identical():
    """Staged kernels with the in-kernel clock compiled in return result
    slots byte-identical to the untimed staged kernels (telemetry on/off
    byte-equality at the stage boundaries)."""
    from dgc_tpu.layout import CARRY_PHASE, OUT0, N_OUT, T_US
    from dgc_tpu.serve.batched import (batched_slice_kernel, idle_carry,
                                       stage_idx_width)

    cls = ShapeClass(2048, 32)
    comb, degrees, k0, max_steps = _class_batch(cls)
    outs = []
    for timing in (False, True):
        carry = idle_carry(4, cls.v_pad, stage_idx_width(_TEST_STAGES))
        reset = np.ones(4, np.int32)
        for _ in range(2000):
            carry = batched_slice_kernel(comb, degrees, k0, max_steps,
                                         reset, carry, planes=cls.planes,
                                         slice_steps=2, timing=timing,
                                         stages=_TEST_STAGES)
            reset = np.zeros(4, np.int32)
            if (np.asarray(carry[CARRY_PHASE]) >= 2).all():
                break
        else:
            raise AssertionError("timed staged loop did not converge")
        outs.append([np.asarray(a) for a in carry[OUT0:OUT0 + N_OUT]])
        if timing:
            t_us = np.asarray(carry[T_US])
            assert (t_us[:3] > 0).all() and (t_us >= 0).all()
    for a, b in zip(*outs):
        assert np.array_equal(a, b)


def test_device_carry_end_to_end_parity():
    """--device-carry end to end: donated slice kernels, on-device lane
    seating, and per-lane result extraction — colors / minimal-k /
    attempt sequences stay byte-identical to the single-graph sweep,
    and the measured device→host bytes undercut the host-mirror path."""
    graphs = [generate_random_graph_fast(500 + 150 * i, avg_degree=6,
                                         seed=90 + i) for i in range(6)]

    def run(device_carry):
        fe = ServeFrontEnd(batch_max=3, window_s=0.05, queue_depth=16,
                           slice_steps=2, stages=_TEST_STAGES,
                           device_carry=device_carry).start()
        try:
            tickets = [fe.submit(g) for g in graphs]
            return ([t.result(timeout=300) for t in tickets],
                    dict(fe.scheduler.stats))
        finally:
            fe.shutdown()

    dev_results, dev_stats = run(True)
    host_results, host_stats = run(False)
    assert dev_stats["recycles"] >= 6
    for g, r_d, r_h in zip(graphs, dev_results, host_results):
        want, want_attempts = _single_graph_reference(g)
        for r in (r_d, r_h):
            assert r.ok
            assert r.minimal_colors == want.minimal_colors
            assert np.array_equal(r.colors, want.colors)
            assert [tuple(a) for a in r.attempts] == want_attempts
    # transfer accounting: both directions counted, device mode strictly
    # cheaper on the downlink (no full-carry materialization per done)
    assert dev_stats["h2d_bytes"] > 0 and dev_stats["d2h_bytes"] > 0
    assert dev_stats["d2h_bytes"] < host_stats["d2h_bytes"]


def test_serve_slice_stage_fields_validate(tmp_path):
    """serve_slice events carry the stage-occupancy + transfer fields
    and the whole log stays schema-clean; serve_summary totals the
    transfer bytes."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.obs.schema import validate_record

    records = []
    logger = RunLogger(echo=False)
    logger.add_sink(records.append)
    fe = ServeFrontEnd(batch_max=2, window_s=0.02, queue_depth=8,
                       slice_steps=1, stages=_TEST_STAGES,
                       logger=logger).start()
    try:
        tickets = [fe.submit(generate_random_graph_fast(
            600, avg_degree=6, seed=s)) for s in range(3)]
        for t in tickets:
            assert t.result(timeout=300).ok
    finally:
        fe.shutdown()
    for rec in records:
        assert validate_record(rec) == [], rec
    slices = [r for r in records if r.get("event") == "serve_slice"]
    assert slices
    assert all("stage_min" in s and "stage_max" in s and "frontier" in s
               and "stage_occupancy" in s for s in slices)
    assert any(s["stage_max"] > 0 for s in slices)   # ladder engaged
    assert all(s["h2d_bytes"] >= 0 and s["d2h_bytes"] >= 0
               for s in slices)
    assert sum(s["h2d_bytes"] for s in slices) > 0
    starts = [r for r in records if r.get("event") == "serve_start"]
    assert starts and starts[0]["stages"] == "custom"
    assert starts[0]["device_carry"] is False


def test_class_ladder_from_tuned_cache(tmp_path):
    """A per-class tuned artifact (serve-<class>.json in the cache
    directory) overrides the derived class ladder under stages='auto' —
    the serve-side tuned-ladder hook."""
    from dgc_tpu.serve.engine import BatchScheduler
    from dgc_tpu.tune import TunedConfig
    from dgc_tpu.tune.cache import TunedConfigCache

    cls = ShapeClass(2048, 8)
    cache = TunedConfigCache(cache_dir=str(tmp_path))
    assert cache.class_config(cls) is None
    TunedConfig(graph_shape_hash=TunedConfigCache.class_key(cls),
                stages=_TEST_STAGES).save(
        str(tmp_path / f"{TunedConfigCache.class_key(cls)}.json"))
    cfg = cache.class_config(cls)
    assert cfg is not None and cfg.stages == _TEST_STAGES
    sched = BatchScheduler(tuned_cache=cache)   # stages="auto"
    assert sched.stages_for(cls) == _TEST_STAGES
    # derived default without the artifact: this class is ladder-free
    assert BatchScheduler().stages_for(cls) is None
    # the override is also parity-safe end to end
    fe = ServeFrontEnd(batch_max=2, window_s=0.02, queue_depth=8,
                       slice_steps=2, tuned_cache=cache).start()
    try:
        g = generate_random_graph_fast(600, avg_degree=6, seed=5)
        res = fe.submit(g).result(timeout=300)
    finally:
        fe.shutdown()
    want, _ = _single_graph_reference(g)
    assert res.ok and res.minimal_colors == want.minimal_colors
    assert np.array_equal(res.colors, want.colors)


def test_recalibration_uses_post_ladder_median():
    """The slice-size recalibration prices the post-ladder regime: the
    sample window restarts when a deeper rung appears (the expensive
    full-table opening slices never skew the median), shallower late
    samples are skipped, and the priced size comes from the median of
    the deepest-rung window."""
    import statistics

    from dgc_tpu.serve.batched import priced_slice_steps
    from dgc_tpu.serve.engine import BatchScheduler

    cls = ShapeClass(2048, 32)
    events = []
    sched = BatchScheduler(timing=True, slice_steps=None,
                           recal_min_slices=3,
                           on_event=lambda k, r: events.append((k, r)))
    # expensive full-table samples at rung 0 …
    for _ in range(2):
        sched._timing_sample(cls, overhead_s=0.004, iter_s=0.030, rung=0)
    # … then the ladder engages: cheap post-ladder samples at rung 2
    post = [0.0011, 0.0009, 0.0010]
    for it in post:
        sched._timing_sample(cls, overhead_s=0.004, iter_s=it, rung=2)
    s1 = sched.resolved_slice_steps(cls, 1)
    assert s1 == priced_slice_steps(0.004, statistics.median(post))
    # a rung-0 sample BEFORE the recal fired would have been skipped,
    # and the window was exactly the rung-2 samples
    [(kind, rec)] = [e for e in events if e[0] == "slice_recalibrated"]
    assert rec["samples"] == 3 and rec["rung"] == 2
    # the pre-ladder mean would have priced a much larger slice: the
    # median of the post-ladder window is what froze
    assert s1 != priced_slice_steps(0.004, 0.030)
    # frozen: more samples never re-price
    for _ in range(5):
        sched._timing_sample(cls, overhead_s=0.1, iter_s=0.1, rung=2)
    assert sched.resolved_slice_steps(cls, 1) == s1


def test_depth_bucket_and_affinity_order():
    from dgc_tpu.serve.engine import (_SweepCall, BatchScheduler,
                                      depth_bucket)

    assert depth_bucket(1) == 1 and depth_bucket(7) == 3
    assert depth_bucket(8) == 4 and depth_bucket(100) == 7

    sched = BatchScheduler(batch_max=4, window_s=0.01)
    calls = [_SweepCall(None, k) for k in (40, 6, 33, 7, 5, 36)]
    ordered = sched._affinity_order(calls, [])
    # the largest same-depth group (k=6,7,5 -> bucket 3) leads, FIFO
    # within it; the deep group follows
    assert [c.k for c in ordered] == [6, 7, 5, 40, 33, 36]
    # live lanes pull the nearest bucket first in continuous mode
    ordered_live = sched._affinity_order(calls, [6, 6, 6])
    assert [c.depth for c in ordered_live[:3]] == [6, 6, 6]
    # starvation guard: a call older than the guard forces strict FIFO
    calls[0].t_enqueue -= 1e6
    assert [c.k for c in sched._affinity_order(calls, [])][0] == 40
    # affinity off: submission order untouched
    off = BatchScheduler(batch_max=4, affinity=False)
    calls2 = [_SweepCall(None, k) for k in (40, 6, 33)]
    assert [c.k for c in off._affinity_order(calls2, [])] == [40, 6, 33]


def test_auto_slice_steps_policy():
    from dgc_tpu.serve.batched import auto_slice_steps

    # more compute per superstep -> fewer supersteps needed to amortize
    # the dispatch; never below lo or above hi
    small = auto_slice_steps(2048 * 8, 1, "tpu")
    big = auto_slice_steps(524288 * 1023, 32, "tpu")
    assert 4 <= big <= small <= 64
    # TPU's ~65 ms dispatch prices longer slices than CPU's sub-ms
    assert auto_slice_steps(32768 * 64, 8, "tpu") >= \
        auto_slice_steps(32768 * 64, 8, "cpu")


def test_warm_classes_precompiles_pad_ladder(tmp_path):
    fe = ServeFrontEnd(batch_max=4, window_s=0.0, queue_depth=8,
                       slice_steps=4).start()
    try:
        with pytest.raises(ValueError):
            fe.warm(["nope"])
        doc = fe.warm(["v2048w8"])
        assert doc == {"classes": 1, "kernels": 3, "stage_bodies": 1,
                       "seconds": doc["seconds"]}   # pads 4, 2, 1
        assert doc["seconds"] > 0
        misses_after_warm = fe.scheduler.stats["compile_misses"]
        g = generate_random_graph_fast(600, avg_degree=4, seed=2)
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        if cls.name == "v2048w8":   # the warm actually covered it
            assert fe.submit(g).result(timeout=300).ok
            assert fe.scheduler.stats["compile_misses"] == misses_after_warm
    finally:
        fe.shutdown()


def test_sync_batches_carry_straggler_waste(tmp_path):
    from dgc_tpu.obs import RunLogger, RunManifest

    logger = RunLogger(jsonl_path=str(tmp_path / "s.jsonl"), echo=False)
    manifest = RunManifest()
    logger.add_sink(manifest)
    fe = ServeFrontEnd(batch_max=4, window_s=0.25, queue_depth=16,
                       mode="sync", logger=logger).start()
    try:
        tickets = [fe.submit(generate_random_graph_fast(
            500 + 100 * i, avg_degree=6, seed=i)) for i in range(4)]
        for t in tickets:
            assert t.result(timeout=300).ok
    finally:
        fe.shutdown()
    logger.close()
    batches = manifest.doc["serve"]["batches"]
    assert batches
    multi = [b for b in batches if b["batch"] > 1]
    assert multi, "window did not coalesce a multi-graph batch"
    for b in batches:
        assert 0.0 <= b["straggler_waste"] < 1.0
        assert b["depth_buckets"] >= 1
    # mixed-size members sweeping different step counts: the dispatch
    # paid a nonzero straggler tail somewhere
    assert any(b["straggler_waste"] > 0 for b in multi)


# -- queue semantics ----------------------------------------------------

def test_backpressure_and_drain(monkeypatch):
    gate = threading.Event()
    done_one = threading.Event()
    real_serve = ServeFrontEnd._serve_one

    def gated(self, req):
        done_one.set()
        gate.wait(30)
        return real_serve(self, req)

    monkeypatch.setattr(ServeFrontEnd, "_serve_one", gated)
    fe = ServeFrontEnd(batch_max=1, workers=1, queue_depth=1,
                       window_s=0.0).start()
    g = generate_random_graph_fast(300, avg_degree=6, seed=0)
    t1 = fe.submit(g)                     # taken by the (gated) worker
    assert done_one.wait(10)
    t2 = fe.submit(g)                     # fills the queue_depth-1 queue
    with pytest.raises(QueueFull):        # backpressure: immediate shed
        fe.submit(g)
    with pytest.raises(QueueFull):        # and after a bounded wait
        fe.submit(g, timeout=0.05)
    assert fe.stats["rejected"] == 2
    gate.set()                            # release; drain must finish all
    fe.shutdown(drain=True)
    assert t1.result(timeout=10).ok and t2.result(timeout=10).ok
    assert fe.stats["completed"] == 2


def test_string_request_ids_round_trip():
    """Replay streams may carry arbitrary JSON ids; a string id must be
    served and echoed back, not crash the auto-id bookkeeping."""
    fe = ServeFrontEnd(batch_max=2, window_s=0.0, queue_depth=8).start()
    try:
        g = generate_random_graph_fast(300, avg_degree=6, seed=3)
        named = fe.submit(g, request_id="req-a")
        auto = fe.submit(g)
        r_named = named.result(timeout=300)
        r_auto = auto.result(timeout=300)
        assert r_named.ok and r_named.request_id == "req-a"
        assert r_auto.ok and isinstance(r_auto.request_id, int)
    finally:
        fe.shutdown()


def test_string_request_id_events_pass_schema():
    """The serve_request event a string-id request emits must validate —
    the schema typed request_id int-only while the front-end accepted
    str ids, so every JSONL replay's run log failed validate_runlog
    (found driving a replay end-to-end; schema fixed to (int, str))."""
    from dgc_tpu.obs.events import RunLogger
    from dgc_tpu.obs.schema import validate_record

    records = []
    logger = RunLogger(echo=False)
    logger.add_sink(records.append)
    fe = ServeFrontEnd(batch_max=2, window_s=0.0, queue_depth=8,
                       logger=logger).start()
    try:
        g = generate_random_graph_fast(300, avg_degree=6, seed=3)
        assert fe.submit(g, request_id="req-s").result(timeout=300).ok
    finally:
        fe.shutdown()
    reqs = [r for r in records if r.get("event") == "serve_request"]
    assert reqs and reqs[0]["request_id"] == "req-s"
    for rec in records:
        assert validate_record(rec) == [], rec


def test_batching_window_coalesces_concurrent_requests():
    fe = ServeFrontEnd(batch_max=4, window_s=0.25, queue_depth=16).start()
    try:
        graphs = [generate_random_graph_fast(600, avg_degree=6, seed=s)
                  for s in range(4)]
        tickets = [fe.submit(g) for g in graphs]
        results = [t.result(timeout=300) for t in tickets]
        assert all(r.ok for r in results)
    finally:
        fe.shutdown()
    # 4 same-class requests inside one window -> they co-reside in one
    # lane pool (continuous mode: every sweep completion is a recycle,
    # and the pool was observed multi-lane wide)
    stats = fe.scheduler.stats
    assert stats["max_live"] >= 2
    assert stats["recycles"] == stats["sweeps"] >= 4


def test_sync_mode_batching_window_coalesces():
    fe = ServeFrontEnd(batch_max=4, window_s=0.25, queue_depth=16,
                       mode="sync").start()
    try:
        graphs = [generate_random_graph_fast(600, avg_degree=6, seed=s)
                  for s in range(4)]
        tickets = [fe.submit(g) for g in graphs]
        results = [t.result(timeout=300) for t in tickets]
        assert all(r.ok for r in results)
    finally:
        fe.shutdown()
    # sync mode keeps the PR 5 batch-complete contract: one batched
    # dispatch for the opening sweep round
    assert fe.scheduler.stats["batches"] < fe.scheduler.stats["sweeps"]
    assert fe.scheduler.stats["slices"] == 0


def test_health_flips_when_supervisor_degrades():
    # a 1-rung ladder too small for any real graph forces the fallback
    # path; a failing first rung then degrades the supervisor
    tiny = ShapeLadder(v_rungs=(8,), w_rungs=(4,))

    def factories(arrays):
        def broken():
            raise RuntimeError("primary engine down")

        def bucketed():
            from dgc_tpu.engine.bucketed import BucketedELLEngine

            return BucketedELLEngine(arrays)

        return [("ell-compact", broken), ("ell-bucketed", bucketed)]

    fe = ServeFrontEnd(ladder=tiny, batch_max=2, queue_depth=8,
                       fallback_factories=factories).start()
    try:
        assert fe.health()["ready"] and not fe.health()["degraded"]
        g = generate_random_graph(60, 6, seed=1)
        res = fe.submit(g).result(timeout=300)
        assert res.ok and not res.batched
        h = fe.health()
        assert h["degraded"] is True
        assert h["backend"] == "ell-bucketed" and h["rung"] == 1
        assert h["ready"] is True      # degraded but still serving
        # parity holds on the fallback path too
        want, _ = _single_graph_reference(g)
        assert res.minimal_colors == want.minimal_colors
    finally:
        fe.shutdown()


def test_shutdown_without_drain_fails_queued_requests(monkeypatch):
    gate = threading.Event()
    taken = threading.Event()
    real_serve = ServeFrontEnd._serve_one

    def gated(self, req):
        taken.set()
        gate.wait(30)
        return real_serve(self, req)

    monkeypatch.setattr(ServeFrontEnd, "_serve_one", gated)
    fe = ServeFrontEnd(batch_max=1, workers=1, queue_depth=4,
                       window_s=0.0).start()
    g = generate_random_graph_fast(300, avg_degree=6, seed=0)
    t1 = fe.submit(g)
    assert taken.wait(10)
    t2 = fe.submit(g)
    gate.set()
    fe.shutdown(drain=False)
    assert t2.result(timeout=10).status == "error"
    assert t1.result(timeout=10).ok      # in-flight request still lands


# -- rung state unit ----------------------------------------------------

def test_rung_state_snapshot():
    from dgc_tpu.resilience.supervisor import RungState

    rs = RungState()
    rs.on_rung("sharded", 0)
    assert rs.snapshot() == {"backend": "sharded", "rung": 0,
                             "retry_pressure": 0, "degraded": False,
                             "ready": True}
    rs.on_retry()
    rs.on_rung("ell", 1)
    snap = rs.snapshot()
    assert snap["degraded"] and snap["retry_pressure"] == 0
    rs.on_exhausted()
    assert rs.snapshot()["ready"] is False


# -- obs integration ----------------------------------------------------

def test_serve_events_validate_against_schema(tmp_path):
    from dgc_tpu.obs import MetricsRegistry, RunLogger, RunManifest

    log = tmp_path / "serve.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    manifest = RunManifest()
    logger.add_sink(manifest)
    fe = ServeFrontEnd(batch_max=2, window_s=0.02, queue_depth=8,
                       logger=logger, registry=MetricsRegistry()).start()
    try:
        tickets = [fe.submit(generate_random_graph_fast(
            500, avg_degree=6, seed=s)) for s in range(3)]
        for t in tickets:
            assert t.result(timeout=300).ok
        fe.health(emit=True)
    finally:
        fe.shutdown()
    logger.close()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from validate_runlog import validate_file

    assert validate_file(str(log)) == []
    serve = manifest.doc["serve"]
    assert serve["config"]["batch_max"] == 2
    assert serve["config"]["mode"] == "continuous"
    assert len(serve["requests"]) == 3
    # continuous mode: the occupancy series lives in the slices slot,
    # and every completed sweep is a lane recycle
    assert serve["slices"] and all(
        0 < s["occupancy"] <= 1 for s in serve["slices"])
    assert serve["recycles"] >= 3
    assert serve["summary"]["completed"] == 3
    # a non-serve manifest never grows the slot (all-defaults-off)
    assert "serve" not in RunManifest().doc


def test_report_run_renders_serve_section(tmp_path, capsys):
    from dgc_tpu.obs import RunLogger

    log = tmp_path / "serve.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    fe = ServeFrontEnd(batch_max=2, window_s=0.0, queue_depth=8,
                       logger=logger).start()
    try:
        fe.submit(generate_random_graph_fast(400, avg_degree=6,
                                             seed=0)).result(timeout=300)
    finally:
        fe.shutdown()
    logger.close()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import report_run

    rc = report_run.main([str(log)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serve:" in out and "requests: 1" in out


# -- tuned-config cache -------------------------------------------------

def test_tuned_config_cache_keys_by_shape(tmp_path):
    from dgc_tpu.tune import TunedConfig
    from dgc_tpu.tune.cache import TunedConfigCache

    calls = []

    def fake_tune(arrays):
        calls.append(arrays.num_vertices)
        from dgc_tpu.tune.config import graph_shape_hash

        return TunedConfig(graph_shape_hash=graph_shape_hash(arrays))

    cache = TunedConfigCache(cache_dir=str(tmp_path))
    g1 = generate_random_graph_fast(500, avg_degree=6, seed=1)
    g2 = generate_random_graph_fast(500, avg_degree=6, seed=1)  # same shape
    g3 = generate_random_graph_fast(500, avg_degree=6, seed=2)  # new shape
    cfg1 = cache.get_or_tune(g1, tune=fake_tune)
    cfg2 = cache.get_or_tune(g2, tune=fake_tune)
    assert cfg1 is cfg2 and calls == [500]     # recurring shape: no replay
    cache.get_or_tune(g3, tune=fake_tune)
    assert len(calls) == 2
    # a fresh process (new cache object) hits the on-disk artifact
    cold = TunedConfigCache(cache_dir=str(tmp_path))
    got = cold.get_or_tune(g1, tune=fake_tune)
    assert len(calls) == 2 and got.graph_shape_hash == cfg1.graph_shape_hash
    assert cold.stats["disk_hits"] == 1


# -- CLI subcommand -----------------------------------------------------

def _run_cli(args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "dgc_tpu.cli", *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_serve_cli_end_to_end(tmp_path):
    reqs = tmp_path / "requests.jsonl"
    reqs.write_text("\n".join(
        json.dumps({"id": i, "node_count": 80, "max_degree": 6, "seed": i})
        for i in range(3)) + "\n")
    results = tmp_path / "results.jsonl"
    log = tmp_path / "run.jsonl"
    manifest = tmp_path / "manifest.json"
    out_dir = tmp_path / "colorings"
    r = _run_cli(["serve", "--requests", str(reqs),
                  "--results", str(results),
                  "--output-colorings", str(out_dir),
                  "--log-json", str(log),
                  "--run-manifest", str(manifest),
                  "--batch-max", "2", "--window-ms", "20",
                  "--device-carry"])
    assert r.returncode == 0, r.stderr
    lines = [json.loads(x) for x in results.read_text().splitlines()]
    assert len(lines) == 3 and all(x["status"] == "ok" for x in lines)
    assert all((out_dir / f"{x['id']}.json").exists() for x in lines)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from validate_runlog import validate_file

    assert validate_file(str(log)) == []
    doc = json.loads(manifest.read_text())
    assert doc["serve"]["summary"]["completed"] == 3


def test_serve_cli_warm_classes_and_modes(tmp_path):
    reqs = tmp_path / "requests.jsonl"
    reqs.write_text("\n".join(
        json.dumps({"id": i, "node_count": 80, "max_degree": 6, "seed": i})
        for i in range(3)) + "\n")
    log = tmp_path / "run.jsonl"
    manifest = tmp_path / "manifest.json"
    r = _run_cli(["serve", "--requests", str(reqs),
                  "--results", str(tmp_path / "results.jsonl"),
                  "--log-json", str(log),
                  "--run-manifest", str(manifest),
                  "--batch-max", "2", "--window-ms", "20",
                  "--slice-steps", "2", "--warm-classes", "v2048w8"])
    assert r.returncode == 0, r.stderr
    doc = json.loads(manifest.read_text())
    serve = doc["serve"]
    assert serve["summary"]["completed"] == 3
    assert serve["summary"]["mode"] == "continuous"
    # warmup reported separately from the serve clock, and the summary
    # carries it (the wide-batch compile penalty satellite)
    assert serve["warmup"]["kernels"] >= 2
    assert serve["summary"]["warmup_s"] == serve["warmup"]["seconds"] > 0
    assert serve["summary"]["recycles"] >= 3
    assert serve["slices"]
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from validate_runlog import validate_file

    assert validate_file(str(log)) == []
    # bad class name: structured CLI error, not a stack trace
    r2 = _run_cli(["serve", "--requests", str(reqs),
                   "--warm-classes", "nope"])
    assert r2.returncode == 2 and "unknown shape class" in r2.stderr
    # sync mode end-to-end (the A/B baseline stays drivable), with the
    # staged ladder disabled (--serve-stages off: the full-table arm)
    r3 = _run_cli(["serve", "--requests", str(reqs),
                   "--results", str(tmp_path / "r3.jsonl"),
                   "--run-manifest", str(tmp_path / "m3.json"),
                   "--serve-mode", "sync", "--batch-max", "2",
                   "--serve-stages", "off"])
    assert r3.returncode == 0, r3.stderr
    doc3 = json.loads((tmp_path / "m3.json").read_text())
    assert doc3["serve"]["summary"]["mode"] == "sync"
    assert doc3["serve"]["batches"]
    assert doc3["serve"]["config"]["stages"] == "off"


def test_serve_cli_metrics_port_and_kernel_timing(tmp_path):
    """Acceptance: during a live ``dgc-tpu serve`` run an HTTP GET on
    --metrics-port returns the current registry in Prometheus text
    format including the per-class latency histograms; --kernel-timing
    lands the sstep/overhead split in the slice events and the latency
    summary in serve_summary."""
    import io
    import urllib.request

    from dgc_tpu.serve.cli import serve_main

    reqs = tmp_path / "requests.jsonl"
    reqs.write_text("\n".join(
        json.dumps({"id": i, "node_count": 800, "max_degree": 8,
                    "seed": i, "gen_method": "fast"})
        for i in range(8)) + "\n")
    log = tmp_path / "run.jsonl"
    manifest = tmp_path / "manifest.json"
    rc_box = {}
    # the CLI's echo logger binds sys.stdout at construction — point it
    # at a plain buffer so the background replay never races pytest's
    # per-test capture teardown
    quiet = io.StringIO()

    def run():
        rc_box["rc"] = serve_main([
            "--requests", str(reqs),
            "--results", str(tmp_path / "results.jsonl"),
            "--log-json", str(log), "--run-manifest", str(manifest),
            "--batch-max", "2", "--window-ms", "10",
            "--slice-steps", "1", "--kernel-timing",
            "--metrics-port", "0", "--no-validate"])

    was_stdout, sys.stdout = sys.stdout, quiet
    try:
        t = threading.Thread(target=run)
        t.start()
        # the CLI logs the bound ephemeral port as a metrics_server event
        port = None
        deadline = time.perf_counter() + 120
        while port is None and time.perf_counter() < deadline:
            if log.exists():
                for line in log.read_text().splitlines():
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("event") == "metrics_server":
                        port = rec["port"]
                        break
            time.sleep(0.05)
        assert port, "metrics_server event never appeared"
        # live scrapes while the replay runs: every GET returns the
        # CURRENT registry; once requests start completing the per-class
        # latency histograms appear in the exposition
        url = f"http://127.0.0.1:{port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
        saw_histograms = False
        while t.is_alive() and not saw_histograms:
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    body = resp.read().decode()
            except OSError:   # server already closed: replay finished
                break
            saw_histograms = "dgc_serve_service_seconds_bucket" in body
            if not saw_histograms:
                time.sleep(0.05)
        assert saw_histograms, \
            "live scrape never showed the latency histograms"
        t.join(timeout=600)
        assert not t.is_alive(), "serve replay did not finish"
    finally:
        sys.stdout = was_stdout
    assert rc_box.get("rc") == 0

    # post-hoc: the scrape-visible registry carried the per-class
    # latency histograms by run end (the request histograms fill as
    # requests complete; the live scrape above may predate the first)
    doc = json.loads(manifest.read_text())
    assert any(k.startswith("dgc_serve_service_seconds")
               for k in doc["metrics"])
    summary = doc["serve"]["summary"]
    assert summary["latency_ms"], "per-class latency summary missing"
    for cls, lm in summary["latency_ms"].items():
        assert lm["p50"] <= lm["p95"] <= lm["p99"]
    # kernel timing: the slice events carry the sstep/overhead split
    timed = [s for s in doc["serve"]["slices"]
             if s.get("sstep_ms") is not None]
    assert timed and all(s["overhead_ms"] >= 0 for s in timed)
    # and the log (spans included) is schema- and structure-clean
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from validate_runlog import validate_file

    assert validate_file(str(log)) == []


def test_serve_cli_bad_request_file(tmp_path):
    reqs = tmp_path / "requests.jsonl"
    reqs.write_text("not json\n")
    r = _run_cli(["serve", "--requests", str(reqs)])
    assert r.returncode == 2


def test_cli_without_serve_subcommand_unchanged(tmp_path):
    # the all-defaults-off invariant: the plain driver still runs and the
    # serve flags don't exist on it
    out = tmp_path / "c.json"
    r = _run_cli(["--node-count", "30", "--max-degree", "4", "--seed", "1",
                  "--backend", "reference-sim",
                  "--output-coloring", str(out)])
    assert r.returncode == 0, r.stderr
    assert out.exists()
    r2 = _run_cli(["--batch-max", "2", "--output-coloring", str(out)])
    assert r2.returncode == 2       # unknown flag outside the subcommand


# -- soak ---------------------------------------------------------------

@pytest.mark.slow
def test_thousand_request_soak():
    fe = ServeFrontEnd(batch_max=8, window_s=0.005, queue_depth=256).start()
    try:
        graphs = [generate_random_graph_fast(200 + (s % 5) * 50,
                                             avg_degree=6, seed=s)
                  for s in range(40)]
        tickets = []
        for i in range(1000):
            tickets.append(fe.submit(graphs[i % len(graphs)],
                                     timeout=60.0))
        results = [t.result(timeout=900) for t in tickets]
    finally:
        fe.shutdown()
    assert all(r.ok for r in results)
    # determinism across the whole replay: same graph -> same answer
    by_graph = {}
    for i, r in enumerate(results):
        key = i % len(graphs)
        if key in by_graph:
            assert r.minimal_colors == by_graph[key].minimal_colors
            assert np.array_equal(r.colors, by_graph[key].colors)
        else:
            by_graph[key] = r
    # lanes actually shared: 1000 sweeps recycled through pools that were
    # observed multi-lane wide (continuous mode has no per-request dispatch)
    assert fe.scheduler.stats["max_live"] >= 2
    assert fe.scheduler.stats["recycles"] == fe.scheduler.stats["sweeps"]

"""Degree-bucketed engine tests."""

import numpy as np
import pytest

from dgc_tpu.engine.base import AttemptStatus
from dgc_tpu.engine.bucketed import BucketedELLEngine, _bucket_widths
from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.generators import generate_random_graph, generate_rmat_graph
from dgc_tpu.ops.validate import validate_coloring


def test_bucket_widths():
    # linear min_width steps below linear_until, then doubling
    assert _bucket_widths(16) == [4, 8, 12, 16]
    assert _bucket_widths(17) == [4, 8, 12, 16, 20]
    assert _bucket_widths(3) == [4]
    assert _bucket_widths(300) == [4, 8, 12, 16, 20, 24, 28, 32, 36, 40,
                                   44, 48, 52, 56, 60, 64, 128, 256, 512]
    assert _bucket_widths(64, min_width=8) == [8, 16, 24, 32, 40, 48, 56, 64]


def test_bucketed_valid_and_parity(small_graphs):
    for g in small_graphs:
        k0 = g.max_degree + 1
        b = find_minimal_coloring(BucketedELLEngine(g), k0, validate=make_validator(g))
        e = find_minimal_coloring(ELLEngine(g), k0)
        assert b.minimal_colors is not None
        assert validate_coloring(g.indptr, g.indices, b.colors).valid
        assert abs(b.minimal_colors - e.minimal_colors) <= 1


def test_bucketed_failure_below_minimal(medium_graph):
    g = medium_graph
    res = find_minimal_coloring(BucketedELLEngine(g), g.max_degree + 1)
    assert BucketedELLEngine(g).attempt(res.minimal_colors - 1).status == AttemptStatus.FAILURE


def test_bucketed_deterministic(medium_graph):
    g = medium_graph
    r1 = BucketedELLEngine(g).attempt(g.max_degree + 1)
    r2 = BucketedELLEngine(g).attempt(g.max_degree + 1)
    assert np.array_equal(r1.colors, r2.colors)


def test_bucketed_heavy_tail():
    # power-law degrees: the case bucketing exists for (SURVEY §7.3)
    g = generate_rmat_graph(2048, avg_degree=8, seed=1, native=False)
    res = find_minimal_coloring(
        BucketedELLEngine(g), g.max_degree + 1, validate=make_validator(g)
    )
    assert res.minimal_colors is not None
    assert validate_coloring(g.indptr, g.indices, res.colors).valid


def test_bucketed_color_windows():
    # complete graph K40 needs 40 colors; the per-bucket color window
    # (width+1 budget, pigeonhole-exact) must cover it with no retry
    v = 40
    edges = np.array([[i, j] for i in range(v) for j in range(i + 1, v)])
    from dgc_tpu.models.arrays import GraphArrays

    g = GraphArrays.from_edge_list(v, edges)
    eng = BucketedELLEngine(g)
    res = eng.attempt(g.max_degree + 1)
    assert res.status == AttemptStatus.SUCCESS
    assert res.colors_used == 40
    assert res.colors.min() == 0 and res.colors.max() == 39


def test_bucketed_isolated_vertices():
    from dgc_tpu.models.arrays import GraphArrays

    g = GraphArrays.from_neighbor_lists([[], [2], [1], []])
    res = BucketedELLEngine(g).attempt(2)
    assert res.status == AttemptStatus.SUCCESS
    assert res.colors[0] == 0 and res.colors[3] == 0


def test_window_cap_retry_widens_on_stall():
    # a 1-plane cap (32 colors) on K40 saturates every window -> STALL ->
    # the retry must widen the windows and succeed (review regression)
    v = 40
    edges = np.array([[i, j] for i in range(v) for j in range(i + 1, v)])
    from dgc_tpu.models.arrays import GraphArrays

    g = GraphArrays.from_edge_list(v, edges)
    eng = BucketedELLEngine(g, max_window_planes=1)
    assert any(32 * p < cb.shape[1] + 1
               for cb, p in zip(eng.combined_buckets, eng.planes))
    res = eng.attempt(g.max_degree + 1)
    assert res.status == AttemptStatus.SUCCESS
    assert res.colors_used == 40
    assert eng._window_cap > 1  # widened during the retry

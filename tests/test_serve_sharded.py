"""Multi-device serve tier: the lane axis sharded over a device mesh.

The contract under test (``serve.batched`` sharded section, ROADMAP
2(a)): laying the ``[B, ...]`` carry and input stacks over
``Mesh(devices, ("lanes",))`` with ``NamedSharding(P("lanes"))`` on
axis 0 changes buffer placement, never the math — every sharded kernel
is byte-identical to its single-device twin, slice-by-slice, across
stage-ladder boundaries and mid-ladder lane re-inits; the scheduler's
mesh mode pads lanes in mesh multiples, balances seats across shards,
and reports per-device occupancy; and fault recovery composes with
sharding (the chaos leg-1 smoke). ``--mesh-devices`` unset (or a
resolved mesh of 1) must leave the whole path byte-identical to the
pre-mesh scheduler.

Runs on the conftest-forced 8-device virtual CPU mesh; skips cleanly
when forcing was impossible.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from dgc_tpu.layout import (CARRY_LEN, CARRY_PHASE, CARRY_RUNG, T_PREV,
                            T_US)
from dgc_tpu.models.graph import Graph
from dgc_tpu.serve import batched as B
from dgc_tpu.serve.shape_classes import (DEFAULT_LADDER, dummy_member,
                                         pad_ladder, pad_member)

pytestmark = [
    pytest.mark.serve,
    pytest.mark.skipif(jax.device_count() < 8,
                       reason="needs 8 (virtual) devices"),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# timing slots hold wall-clock samples — the ONLY carry slots allowed to
# differ between two equivalent runs
_CLOCK_SLOTS = (T_US, T_PREV)


def _batch(cls, graphs, pad_to):
    members = [pad_member(g.arrays, cls) for g in graphs]
    dummy = dummy_member(cls)
    members += [dummy] * (pad_to - len(members))
    return (np.stack([m.comb for m in members]),
            np.stack([m.degrees for m in members]),
            np.array([m.k0 for m in members], np.int32),
            np.array([m.max_steps for m in members], np.int32))


@pytest.fixture(scope="module")
def mesh():
    return B.lane_mesh("auto")


@pytest.fixture(scope="module")
def cls():
    return DEFAULT_LADDER.class_for(1800, 16)


@pytest.fixture(scope="module")
def batch8(cls):
    graphs = [Graph.generate(1500 + 40 * i, 10, seed=i, method="fast")
              for i in range(6)]
    return _batch(cls, graphs, 8)


# ---------------------------------------------------------------------------
# mesh resolution
# ---------------------------------------------------------------------------

def test_mesh_resolution_auto_and_explicit():
    assert B.mesh_device_count("auto") == 8
    assert B.mesh_device_count(None) == 8
    assert B.mesh_device_count(2) == 2
    with pytest.raises(ValueError, match="power of two"):
        B.mesh_device_count(3)
    with pytest.raises(ValueError, match="exceeds"):
        B.mesh_device_count(16)
    m = B.lane_mesh(4)
    assert m.devices.size == 4 and m.axis_names == ("lanes",)


def test_mesh_unset_or_one_keeps_the_exact_path():
    """mesh_devices=None and mesh_devices=1 are the byte-identical
    pre-mesh scheduler: no mesh object, unchanged compile-cache keys."""
    from dgc_tpu.serve.engine import BatchScheduler

    base = BatchScheduler(batch_max=4)
    one = BatchScheduler(batch_max=4, mesh_devices=1)
    assert base.mesh is None and one.mesh is None
    assert base.mesh_devices == 0 and one.mesh_devices == 0
    assert base.mesh_snapshot() is None
    c = DEFAULT_LADDER.class_for(300, 8)
    base._kernel_for(c, 2)
    one._kernel_for(c, 2)
    assert set(base._kernels) == set(one._kernels)
    sharded = BatchScheduler(batch_max=4, mesh_devices=8)
    assert sharded.mesh is not None and sharded.mesh_devices == 8
    sharded._kernel_for(c, 8)
    (key,) = sharded._kernels
    # mesh size + degrade/restore generation (0 = the pre-degrade mesh)
    assert key[-3:] == ("mesh", 8, 0)


def test_pad_ladder_mesh_floor():
    assert pad_ladder(8) == (8, 4, 2, 1)
    assert pad_ladder(8, min_pad=8) == (8,)
    assert pad_ladder(32, min_pad=8) == (32, 16, 8)
    # the non-pow2 batch_max pad never dispatches in mesh mode
    assert pad_ladder(6, min_pad=4) == (8, 4)


# ---------------------------------------------------------------------------
# kernel byte-identity: sharded vs single-device
# ---------------------------------------------------------------------------

def test_sharded_sweep_kernel_matches_unsharded(mesh, cls, batch8):
    comb, degrees, k0, ms = batch8
    out_u = B.batched_sweep_kernel(comb, degrees, k0, ms,
                                   planes=cls.planes)
    out_s = B.batched_sweep_kernel_sharded(mesh, comb, degrees, k0, ms,
                                           planes=cls.planes)
    for j, (a, b) in enumerate(zip(out_u, out_s)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f"slot {j}"
    # the outputs really are lane-sharded over the full mesh
    assert len(out_s[0].sharding.device_set) == 8


def test_sharded_slice_s1_stage_reentry_byte_identical(mesh, cls, batch8):
    """S=1 worst case: every superstep crosses a slice re-entry, and the
    explicit 3-rung ladder makes the walk cross stage transitions — the
    sharded carry must round-trip byte-identically at every boundary
    (the slice↔stage re-entry satellite, under the mesh)."""
    comb, degrees, k0, ms = batch8
    stages = ((None, 512), (512, 128), (128, 0))
    a0 = B.stage_idx_width(stages)
    carry_u = B.idle_carry(8, cls.v_pad, a0)
    carry_s = tuple(np.copy(a) for a in carry_u)
    reset = np.ones(8, np.int32)
    max_rung = 0
    for it in range(600):
        carry_u = B.batched_slice_kernel(
            comb, degrees, k0, ms, reset, carry_u, planes=cls.planes,
            slice_steps=1, stages=stages)
        carry_s = B.batched_slice_kernel_sharded(
            mesh, comb, degrees, k0, ms, reset, carry_s,
            planes=cls.planes, slice_steps=1, stages=stages)
        reset = np.zeros(8, np.int32)
        for j in range(CARRY_LEN):
            if j in _CLOCK_SLOTS:
                continue
            assert np.array_equal(np.asarray(carry_u[j]),
                                  np.asarray(carry_s[j])), \
                f"slot {j} diverged at slice {it}"
        phase = np.asarray(carry_s[CARRY_PHASE])
        rungs = np.asarray(carry_s[CARRY_RUNG])
        if (phase < 2).any():
            max_rung = max(max_rung, int(rungs[phase < 2].max()))
        if (phase >= 2).all():
            break
    else:
        pytest.fail("batch never finished")
    # the ladder actually engaged — the equality above covered real
    # stage transitions, not a degenerate full-table-only walk
    assert max_rung >= 1


def test_sharded_lane_reinit_mid_ladder(mesh, cls, batch8):
    """Reset one lane with NEW inputs while co-resident lanes sit
    mid-ladder: the sharded re-init must match the unsharded one and
    co-residents must stay byte-identical (lane recycling under the
    mesh)."""
    comb, degrees, k0, ms = batch8
    stages = ((None, 512), (512, 128), (128, 0))
    a0 = B.stage_idx_width(stages)
    carry_u = B.idle_carry(8, cls.v_pad, a0)
    carry_s = tuple(np.copy(a) for a in carry_u)
    reset = np.ones(8, np.int32)
    swapped = False
    comb_u, deg_u, k0_u, ms_u = comb, degrees, k0, ms
    for it in range(600):
        carry_u = B.batched_slice_kernel(
            comb_u, deg_u, k0_u, ms_u, reset, carry_u,
            planes=cls.planes, slice_steps=1, stages=stages)
        carry_s = B.batched_slice_kernel_sharded(
            mesh, comb_u, deg_u, k0_u, ms_u, reset, carry_s,
            planes=cls.planes, slice_steps=1, stages=stages)
        reset = np.zeros(8, np.int32)
        for j in range(CARRY_LEN):
            if j in _CLOCK_SLOTS:
                continue
            assert np.array_equal(np.asarray(carry_u[j]),
                                  np.asarray(carry_s[j])), \
                f"slot {j} diverged at slice {it}"
        phase = np.asarray(carry_s[CARRY_PHASE])
        rungs = np.asarray(carry_s[CARRY_RUNG])
        live = phase < 2
        if (not swapped and live.any()
                and rungs[live].max() >= 1):
            # swap lane 0 for a fresh graph mid-ladder (the scheduler's
            # recycle: write inputs, raise reset)
            g = Graph.generate(1600, 10, seed=99, method="fast")
            m = pad_member(g.arrays, cls)
            comb_u = comb_u.copy()
            deg_u = deg_u.copy()
            k0_u = k0_u.copy()
            ms_u = ms_u.copy()
            comb_u[0] = m.comb
            deg_u[0] = m.degrees
            k0_u[0] = m.k0
            ms_u[0] = m.max_steps
            reset = np.zeros(8, np.int32)
            reset[0] = 1
            swapped = True
        if (phase >= 2).all() and swapped:
            break
    else:
        pytest.fail("batch never finished (or never reached the ladder)")
    assert swapped


def test_seat_permute_resize_sharded_match(mesh, cls, batch8):
    comb, degrees, k0, ms = batch8
    lane_sh = B.lane_sharding(mesh)
    a0 = 1
    carry = B.idle_carry(8, cls.v_pad, a0)
    dev = tuple(jax.device_put(a, lane_sh) for a in carry)
    base_s = tuple(jax.device_put(a, lane_sh)
                   for a in B.idle_carry(8, cls.v_pad, a0))
    base_u = tuple(jax.device_put(a)
                   for a in B.idle_carry(8, cls.v_pad, a0))
    src = np.array([1, 4, 6], np.int32)
    dst = np.arange(3, dtype=np.int32)
    perm_s = B.permute_carry_kernel_sharded(mesh, dev, base_s, src, dst)
    perm_u = B.permute_carry_kernel(carry, base_u, src, dst)
    for j in range(CARRY_LEN):
        assert np.array_equal(np.asarray(perm_s[j]),
                              np.asarray(perm_u[j]))
    m = pad_member(Graph.generate(900, 8, seed=5, method="fast").arrays,
                   cls)
    out = B.seat_lane_kernel_sharded(
        mesh, jax.device_put(comb, lane_sh),
        jax.device_put(degrees, lane_sh), jax.device_put(k0, lane_sh),
        jax.device_put(ms, lane_sh),
        jax.device_put(np.zeros(8, np.int32), lane_sh),
        np.int32(5), m.comb, m.degrees, np.int32(m.k0),
        np.int32(m.max_steps))
    assert np.array_equal(np.asarray(out[0])[5], m.comb)
    assert int(np.asarray(out[4])[5]) == 1
    # untouched lanes unchanged by the shard-local scatter
    assert np.array_equal(np.asarray(out[0])[0], comb[0])
    dummy = dummy_member(cls)
    src_map = np.array([0, 2, 8, 8, 8, 8, 8, 8], np.int32)
    rz = B.resize_inputs_kernel_sharded(
        mesh, jax.device_put(comb, lane_sh),
        jax.device_put(degrees, lane_sh), jax.device_put(k0, lane_sh),
        jax.device_put(ms, lane_sh), src_map, dummy.comb, dummy.degrees,
        np.int32(1), np.int32(dummy.max_steps))
    assert np.array_equal(np.asarray(rz[0])[0], comb[0])
    assert np.array_equal(np.asarray(rz[0])[1], comb[2])
    assert np.array_equal(np.asarray(rz[0])[2], dummy.comb)
    assert int(np.asarray(rz[4]).sum()) == 0


# ---------------------------------------------------------------------------
# scheduler: pads, balanced seating, per-device occupancy, events
# ---------------------------------------------------------------------------

def test_pool_pads_mesh_multiples_and_balanced_seating(mesh, cls):
    from dgc_tpu.serve.engine import _LanePool, _SweepCall

    pool = _LanePool(cls, 1, dummy_member(cls), mesh=mesh)
    assert pool.b_pad == 8                     # floored at the mesh size
    m = pad_member(Graph.generate(600, 8, seed=1, method="fast").arrays,
                   cls)
    lanes = [pool.fill(_SweepCall(m, m.k0)) for _ in range(4)]
    # one seat per shard before any shard takes a second lane
    assert len({i // (pool.b_pad // pool.mesh_n) for i in lanes}) == 4
    assert pool.device_live() == [1, 1, 1, 1, 0, 0, 0, 0]
    pool.fill(_SweepCall(m, m.k0))
    assert sum(pool.device_live()) == 5
    assert max(pool.device_live()) == 1        # still one lane per shard


def test_e2e_mesh_parity_events_and_runlog(tmp_path):
    """Full stack under the mesh: colors/minimal-k/attempts equal the
    single-graph fused sweep, serve events carry schema-valid mesh
    fields, and the written run log validates end to end."""
    from dgc_tpu.engine.compact import CompactFrontierEngine
    from dgc_tpu.engine.minimal_k import (find_minimal_coloring,
                                          make_reducer, make_validator)
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.serve.queue import ServeFrontEnd
    from tools.validate_runlog import validate_file

    graphs = [Graph.generate(700 + 60 * i, 6, seed=i, method="fast")
              for i in range(5)]
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    fe = ServeFrontEnd(batch_max=8, window_s=0.02, queue_depth=32,
                       mesh_devices=8, slice_steps=2,
                       logger=logger).start()
    attempts = {}
    try:
        tickets = [fe.submit(g.arrays, request_id=i)
                   for i, g in enumerate(graphs)]
        results = [t.result(timeout=300) for t in tickets]
        snap = fe.scheduler.mesh_snapshot()
    finally:
        fe.shutdown()
        logger.close()
    assert snap["mesh_devices"] == 8
    assert len(snap["device_occupancy"]) == 8
    assert any(x > 0 for x in snap["device_occupancy"])
    for g, r in zip(graphs, results):
        seq = []
        arr = g.arrays
        ref = find_minimal_coloring(
            CompactFrontierEngine(arr), initial_k=arr.max_degree + 1,
            validate=make_validator(arr),
            on_attempt=lambda res, val: seq.append(
                (int(res.k), res.status.name, int(res.supersteps))),
            post_reduce=make_reducer(arr))
        assert r.ok and r.batched
        assert r.minimal_colors == ref.minimal_colors
        assert np.array_equal(r.colors, ref.colors)
        assert list(map(tuple, r.attempts)) == seq
        attempts[r.request_id] = r.attempts
    assert validate_file(str(log)) == []
    recs = [json.loads(ln) for ln in open(log)]
    start = next(r for r in recs if r["event"] == "serve_start")
    assert start["mesh_devices"] == 8
    slices = [r for r in recs if r["event"] == "serve_slice"]
    assert slices
    for s in slices:
        assert s["mesh_devices"] == 8
        assert len(s["device_occupancy"]) == 8
        assert abs(sum(x * (s["b_pad"] // 8)
                       for x in s["device_occupancy"]) - s["live"]) < 1e-6


def test_mesh_off_emits_no_mesh_fields(tmp_path):
    """The unsharded event stream must stay byte-identical: no mesh
    fields anywhere when --mesh-devices is unset."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.serve.queue import ServeFrontEnd

    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    g = Graph.generate(400, 5, seed=2, method="fast")
    fe = ServeFrontEnd(batch_max=2, window_s=0.0, logger=logger).start()
    try:
        assert fe.submit(g.arrays).result(timeout=300).ok
    finally:
        fe.shutdown()
        logger.close()
    for ln in open(log):
        assert "mesh_devices" not in ln and "device_occupancy" not in ln


def test_sync_mode_mesh_batch_fields():
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.serve.queue import ServeFrontEnd

    logger = RunLogger(echo=False)
    records = []
    logger.add_sink(records.append)
    graphs = [Graph.generate(500 + 40 * i, 6, seed=i, method="fast")
              for i in range(3)]
    fe = ServeFrontEnd(batch_max=4, window_s=0.05, mode="sync",
                       mesh_devices=8, logger=logger).start()
    try:
        tickets = [fe.submit(g.arrays) for g in graphs]
        assert all(t.result(timeout=300).ok for t in tickets)
    finally:
        fe.shutdown()
    batches = [r for r in records if r["event"] == "serve_batch"]
    assert batches
    for b in batches:
        assert b["mesh_devices"] == 8
        assert b["b_pad"] % 8 == 0
        assert len(b["device_occupancy"]) == 8


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def _write_requests(tmp_path, n=3):
    req = tmp_path / "reqs.jsonl"
    with open(req, "w") as fh:
        for i in range(n):
            fh.write(json.dumps({"id": i, "node_count": 300,
                                 "max_degree": 5, "seed": i,
                                 "gen_method": "fast"}) + "\n")
    return req


def test_serve_cli_mesh_devices_flag(tmp_path, capsys):
    from dgc_tpu.serve.cli import serve_main
    from tools.validate_runlog import validate_file

    req = _write_requests(tmp_path)
    log = tmp_path / "log.jsonl"
    out = tmp_path / "results.jsonl"
    rc = serve_main(["--requests", str(req), "--results", str(out),
                     "--mesh-devices", "8", "--batch-max", "4",
                     "--log-json", str(log), "--no-trace"])
    assert rc == 0
    assert validate_file(str(log)) == []
    recs = [json.loads(ln) for ln in open(log)]
    summ = next(r for r in recs if r["event"] == "serve_summary")
    assert summ["mesh_devices"] == 8
    assert len(summ["device_occupancy"]) == 8
    results = [json.loads(ln) for ln in open(out)]
    assert all(r["status"] == "ok" for r in results)


def test_serve_cli_bad_mesh_devices_exits_2(tmp_path, capsys):
    from dgc_tpu.serve.cli import serve_main

    req = _write_requests(tmp_path, n=1)
    assert serve_main(["--requests", str(req),
                       "--mesh-devices", "3"]) == 2
    assert "--mesh-devices" in capsys.readouterr().err
    assert serve_main(["--requests", str(req),
                       "--mesh-devices", "lots"]) == 2


# ---------------------------------------------------------------------------
# chaos leg-1 smoke: fault recovery composes with sharding
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_mesh_dispatch_fault_recovers_bit_identical(tmp_path):
    """The crash-safe serve policies (pool teardown, reseat, quarantine
    budget) operate on the SHARDED pool exactly as on the single-device
    one: an injected dispatch abort under the mesh recovers with
    bit-identical colors, and the rebuild event lands."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.resilience import faults
    from dgc_tpu.serve.queue import ServeFrontEnd
    from tools.validate_runlog import validate_file

    g = Graph.generate(400, 5, seed=3, method="fast")
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    fe = ServeFrontEnd(batch_max=2, workers=2, queue_depth=16,
                       window_s=0.0, dispatch_timeout=4.0,
                       mesh_devices=8, logger=logger).start()
    try:
        baseline = fe.submit(g.arrays).result(timeout=300)
        assert baseline.status == "ok" and baseline.batched
        plane = faults.FaultPlane(
            faults.FaultSchedule.parse("serve_dispatch@1=transient"))
        with faults.injected(plane):
            res = fe.submit(g.arrays).result(timeout=300)
        assert plane.fired_snapshot()
        assert res.status == "ok"
        assert np.array_equal(np.asarray(res.colors),
                              np.asarray(baseline.colors))
    finally:
        fe.shutdown()
        logger.close()
    assert validate_file(str(log)) == []
    rebuilds = [json.loads(ln) for ln in open(log)
                if '"lane_rebuild"' in ln]
    assert rebuilds and rebuilds[0]["reason"] == "abort"
    assert rebuilds[0]["reseated"] == 1


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_serve_leg1_smoke_with_mesh(tmp_path):
    """tools/chaos_serve.py leg 1 with --mesh-devices on: the seeded
    serve-point schedule battery must recover (or structured-abort)
    over the SHARDED stack with ok-colors bit-identical to fault-free —
    fault recovery composes with sharding end to end."""
    import subprocess

    report = tmp_path / "chaos_serve_mesh.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_serve.py"),
         "--schedules", "2", "--kills", "0", "--clients", "2",
         "--requests-per-client", "2", "--nodes", "400", "--degree", "5",
         "--mesh-devices", "8",
         "--deadline", "240", "--report", str(report)],
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        cwd=REPO, capture_output=True, text=True, timeout=560,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["chaos_serve"]["failed"] == 0

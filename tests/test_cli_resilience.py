"""CLI resilience flags end-to-end: supervised sweep, fault injection,
structured abort, ladder, and the zero-overhead-off guarantee."""

import json

import pytest

from dgc_tpu.cli import main
from dgc_tpu.resilience.supervisor import STRUCTURED_ABORT_RC

pytestmark = pytest.mark.chaos


def _colors(path):
    return json.load(open(path))


def _gen_args(tmp_path, name, *extra):
    return ["--node-count", "60", "--max-degree", "6", "--seed", "2",
            "--output-coloring", str(tmp_path / name), "--backend",
            "reference-sim", *extra]


def test_resilient_no_faults_bit_identical_to_plain(tmp_path):
    # resilience ON but quiet must not change the output (zero-overhead
    # acceptance criterion, behavior half)
    assert main(_gen_args(tmp_path, "plain.json")) == 0
    assert main(_gen_args(tmp_path, "res.json", "--retries", "3",
                          "--attempt-timeout", "30")) == 0
    assert _colors(tmp_path / "plain.json") == _colors(tmp_path / "res.json")


def test_transient_fault_recovered_bit_identical(tmp_path):
    assert main(_gen_args(tmp_path, "plain.json")) == 0
    log = tmp_path / "run.jsonl"
    rc = main(_gen_args(tmp_path, "faulted.json", "--retries", "3",
                        "--inject-faults", "attempt@1=transient",
                        "--log-json", str(log)))
    assert rc == 0
    assert _colors(tmp_path / "plain.json") == _colors(tmp_path / "faulted.json")
    kinds = [json.loads(l)["event"] for l in log.read_text().splitlines()]
    assert "fault_injected" in kinds and "retry" in kinds


def test_oom_falls_down_ladder(tmp_path):
    # primary ell OOMs once -> ladder degrades; run still exits 0 with a
    # valid coloring and the fallback is in the event stream
    log = tmp_path / "run.jsonl"
    rc = main(["--node-count", "60", "--max-degree", "6", "--seed", "2",
               "--output-coloring", str(tmp_path / "c.json"),
               "--backend", "ell", "--retries", "2",
               "--inject-faults", "attempt@1=oom", "--log-json", str(log)])
    assert rc == 0
    events = [json.loads(l) for l in log.read_text().splitlines()]
    fb = [e for e in events if e["event"] == "fallback"]
    assert fb and fb[0]["from_backend"] == "ell"
    assert fb[0]["error_class"] == "resource"


def test_explicit_fallback_ladder(tmp_path):
    log = tmp_path / "run.jsonl"
    rc = main(["--node-count", "60", "--max-degree", "6", "--seed", "2",
               "--output-coloring", str(tmp_path / "c.json"),
               "--backend", "ell", "--fallback-ladder", "reference-sim",
               "--inject-faults", "attempt@1=oom", "--log-json", str(log)])
    assert rc == 0
    events = [json.loads(l) for l in log.read_text().splitlines()]
    fb = [e for e in events if e["event"] == "fallback"]
    assert fb[0]["to_backend"] == "reference-sim"


def test_exhausted_ladder_is_structured_abort(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    out = tmp_path / "c.json"
    rc = main(_gen_args(tmp_path, "c.json", "--retries", "1",
                        "--inject-faults", "attempt@1=fatal",
                        "--log-json", str(log)))
    assert rc == STRUCTURED_ABORT_RC == 114
    assert "structured abort" in capsys.readouterr().err
    assert not out.exists()  # no partial artifact, never garbage
    events = [json.loads(l) for l in log.read_text().splitlines()]
    ab = [e for e in events if e["event"] == "structured_abort"]
    assert ab and ab[0]["rc"] == 114 and ab[0]["ladder"] == ["reference-sim"]


def test_bad_fault_spec_rejected(tmp_path, capsys):
    rc = main(_gen_args(tmp_path, "c.json", "--inject-faults", "bogus"))
    assert rc == 2
    assert "Bad --inject-faults" in capsys.readouterr().err


def test_unknown_ladder_backend_rejected(tmp_path, capsys):
    rc = main(_gen_args(tmp_path, "c.json", "--fallback-ladder", "warp-drive"))
    assert rc == 2
    assert "warp-drive" in capsys.readouterr().err


def test_resilience_events_land_in_manifest_and_validate(tmp_path):
    # the manifest's resilience slot + the JSONL both carry the events, and
    # the log passes the obs schema drift guard
    log = tmp_path / "run.jsonl"
    man = tmp_path / "manifest.json"
    rc = main(_gen_args(tmp_path, "c.json", "--retries", "3",
                        "--inject-faults", "attempt@1=transient",
                        "--log-json", str(log), "--run-manifest", str(man)))
    assert rc == 0
    from tools.validate_runlog import validate_file

    assert validate_file(str(log)) == []
    doc = json.load(open(man))
    assert len(doc["resilience"]["faults"]) == 1
    assert len(doc["resilience"]["retries"]) == 1
    metrics = doc["metrics"]
    assert any(k.startswith("dgc_retries_total") for k in metrics)


def test_checkpoint_resume_event_on_restart(tmp_path):
    # a resilient checkpointed run that already finished re-reports via a
    # checkpoint_resume event on the next invocation
    ck = tmp_path / "ck"
    args = _gen_args(tmp_path, "c.json", "--retries", "1",
                     "--checkpoint-dir", str(ck))
    assert main(args) == 0
    log = tmp_path / "second.jsonl"
    assert main(args + ["--log-json", str(log)]) == 0
    events = [json.loads(l) for l in log.read_text().splitlines()]
    res = [e for e in events if e["event"] == "checkpoint_resume"]
    assert res and res[0]["done"] is True

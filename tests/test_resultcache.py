"""Content-addressed result cache + single-flight coalescing tests
(dgc_tpu.serve.resultcache and its netfront wiring, ROADMAP 2(c)):
hash canonicalization, LRU/disk-store semantics (torn entries are
misses), the end-to-end cache-hit request path (byte-identical colors,
journaled + metered ``cached`` deliveries), the N-concurrent-identical
hammer (exactly one compute), leader-failure follower promotion,
kill-resume replay of a coalesced group, tenant isolation of usage,
usage conservation with cached deliveries, the cache-off byte-identity
contract, and the tuned-config cache's exact-hash fast path."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.models.graph import Graph
from dgc_tpu.obs import RunLogger
from dgc_tpu.obs.metrics import MetricsRegistry
from dgc_tpu.obs.usage import conservation_problems, journal_totals
from dgc_tpu.serve.netfront import NetFront, TicketJournal
from dgc_tpu.serve.queue import QueueFull, ServeFrontEnd, ServeResult
from dgc_tpu.serve.resultcache import (CachedResult, ResultCache,
                                       graph_content_hash)
from dgc_tpu.tune.cache import TunedConfigCache
from dgc_tpu.tune.config import TunedConfig, graph_shape_hash
from tools.validate_runlog import validate_file

pytestmark = pytest.mark.serve


# -- fixtures -----------------------------------------------------------

class _CountingFront(ServeFrontEnd):
    """No-jax front end that counts ``_serve_one`` invocations — the
    single-flight assertions hinge on exactly how many computes ran.
    Colors are a pure function of V, so identical submissions get
    byte-identical results the way deterministic engines guarantee."""

    def __init__(self, *a, gate=None, **kw):
        super().__init__(*a, **kw)
        self._gate = gate
        self.computes = 0
        self._count_lock = threading.Lock()

    def _serve_one(self, req):
        with self._count_lock:
            self.computes += 1
        t0 = time.perf_counter()
        if self._gate is not None:
            self._gate.wait(30)
        v = int(len(req.arrays.indptr) - 1)
        return ServeResult(
            request_id=req.request_id, status="ok",
            colors=np.arange(v, dtype=np.int32) % 3, minimal_colors=3,
            attempts=[(3, "SUCCESS", 5)],
            queue_s=t0 - req.t_submit,
            service_s=time.perf_counter() - t0,
            batched=False, shape_class=None)


class _WedgeSubmitFront(_CountingFront):
    """Front whose NEXT ``submit`` wedges (holding the caller inside the
    listener's leader path) and then raises ``QueueFull`` — the
    deterministic window for attaching a follower before leader loss."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.submit_wedged = threading.Event()
        self.submit_release = threading.Event()
        self._fail_next = False

    def arm_failure(self):
        self._fail_next = True

    def submit(self, *a, **kw):
        if self._fail_next:
            self._fail_next = False
            self.submit_wedged.set()
            self.submit_release.wait(30)
            raise QueueFull("synthetic backpressure", queue_depth=1,
                            capacity=1, retry_after_s=0.5)
        return super().submit(*a, **kw)


def _stack(tmp_path, logger=None, gate=None, cache=None, registry=None,
           front_cls=_CountingFront, **nf_kw):
    front = front_cls(batch_max=2, workers=2, queue_depth=32,
                      window_s=0.0, logger=logger, gate=gate).start()
    nf = NetFront(front, logger=logger, registry=registry,
                  journal_dir=str(tmp_path / "journal"),
                  resultcache=cache, **nf_kw).start()
    return front, nf


def _post(port, path, doc, tenant=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Dgc-Tenant": tenant} if tenant else {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {})


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {})


def _poll(port, ticket, timeout=30.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        st, doc = _get(port, f"/v1/result/{ticket}?colors=1")
        if st != 202:
            return st, doc
        time.sleep(0.01)
    raise TimeoutError(f"ticket {ticket} never terminal")


_SPEC = {"node_count": 24, "max_degree": 3, "seed": 5,
         "gen_method": "fast"}


def _entry(v=4, **kw):
    return CachedResult(colors=np.arange(v, dtype=np.int32) % 3,
                        minimal_colors=3, attempts=1, **kw)


# -- content hash -------------------------------------------------------

def test_content_hash_deterministic_and_splits_on_identity():
    a = Graph.generate(40, 4, seed=7, method="fast").arrays
    b = Graph.generate(40, 4, seed=7, method="fast").arrays
    c = Graph.generate(40, 4, seed=8, method="fast").arrays
    h = graph_content_hash(a, k0=5, engine_key="e1")
    assert h == graph_content_hash(b, k0=5, engine_key="e1")
    assert h.startswith("dgcgraph-")
    # a different graph, a different k0, and a different engine
    # identity must each get their own key
    assert h != graph_content_hash(c, k0=5, engine_key="e1")
    assert h != graph_content_hash(a, k0=6, engine_key="e1")
    assert h != graph_content_hash(a, k0=5, engine_key="e2")


def test_content_hash_neighbor_order_invariant():
    """Row-internal neighbor order is engine-irrelevant; externally
    loaded CSRs may be unsorted and must still collide with the sorted
    image of the same adjacency."""
    tri_sorted = GraphArrays(indptr=np.array([0, 2, 4, 6], np.int32),
                             indices=np.array([1, 2, 0, 2, 0, 1],
                                              np.int32))
    tri_shuffled = GraphArrays(indptr=np.array([0, 2, 4, 6], np.int32),
                               indices=np.array([2, 1, 2, 0, 1, 0],
                                                np.int32))
    assert (graph_content_hash(tri_sorted, k0=3)
            == graph_content_hash(tri_shuffled, k0=3))
    # row MEMBERSHIP is positional: moving an edge between rows is a
    # different adjacency even with the same multiset of indices
    other = GraphArrays(indptr=np.array([0, 1, 4, 6], np.int32),
                        indices=np.array([1, 0, 0, 2, 1, 1], np.int32))
    assert (graph_content_hash(tri_sorted, k0=3)
            != graph_content_hash(other, k0=3))


# -- cache storage tiers ------------------------------------------------

def test_capacity_must_be_positive():
    with pytest.raises(ValueError):
        ResultCache(0)


def test_lru_eviction_order():
    rc = ResultCache(2)
    rc.put("k1", _entry())
    rc.put("k2", _entry())
    rc.get("k1")                       # k1 now most-recent
    rc.put("k3", _entry())             # evicts k2, the cold end
    assert rc.get("k2") is None
    assert rc.get("k1") is not None and rc.get("k3") is not None
    snap = rc.snapshot()
    assert snap["evictions"] == 1 and snap["entries"] == 2
    assert snap["capacity"] == 2 and snap["disk"] is False


def test_disk_store_roundtrip_across_instances(tmp_path):
    writer = ResultCache(4, cache_dir=str(tmp_path / "store"))
    ent = _entry(v=9, source_ticket="t00000001", shape_class="v64w8")
    writer.put("kx", ent)
    reader = ResultCache(4, cache_dir=str(tmp_path / "store"))
    got = reader.get("kx")
    assert got is not None and got[1] == "disk"
    assert np.array_equal(got[0].colors, ent.colors)
    assert got[0].colors.dtype == np.int32
    assert got[0].minimal_colors == 3
    assert got[0].source_ticket == "t00000001"
    assert got[0].shape_class == "v64w8"
    # the disk hit is promoted: the second lookup is a memory hit
    assert reader.get("kx")[1] == "mem"
    snap = reader.snapshot()
    assert snap["disk_hits"] == 1 and snap["mem_hits"] == 1


def test_torn_disk_entry_is_a_miss_not_an_error(tmp_path):
    store = tmp_path / "store"
    rc = ResultCache(4, cache_dir=str(store))
    (store / "kt.json").write_text('{"version": 1, "key": "kt", "col')
    assert rc.get("kt") is None
    # key/version mismatches are the same class of fault: a writer
    # publishing under the wrong name must never serve wrong colors
    (store / "km.json").write_text(json.dumps(_entry().to_doc("other")))
    assert rc.get("km") is None
    snap = rc.snapshot()
    assert snap["corrupt"] == 2 and snap["misses"] == 2
    # a store overwrites the torn entry and the key serves again
    rc.put("kt", _entry())
    assert ResultCache(4, cache_dir=str(store)).get("kt") is not None


# -- disk-store GC ------------------------------------------------------

def test_disk_gc_ttl_evicts_stale_entries(tmp_path):
    store = tmp_path / "store"
    rc = ResultCache(4, cache_dir=str(store), ttl_s=60.0)
    rc.put("old", _entry())
    old_path = store / "old.json"
    past = time.time() - 120
    os.utime(old_path, (past, past))
    # the store-time sweep rides put(): the fresh entry survives, the
    # stale one unlinks, and the eviction record surfaces to the caller
    evicted = rc.put("new", _entry())
    assert [e["key"] for e in evicted] == ["old"]
    assert evicted[0]["reason"] == "ttl" and evicted[0]["bytes"] > 0
    assert not old_path.exists() and (store / "new.json").exists()
    assert rc.snapshot()["disk_evictions"] == 1
    # the dead entry is a clean miss for a fresh instance
    assert ResultCache(4, cache_dir=str(store)).get("old") is None


def test_disk_gc_max_bytes_evicts_oldest_first(tmp_path):
    store = tmp_path / "store"
    rc = ResultCache(8, cache_dir=str(store))   # no bounds: no GC yet
    for i, key in enumerate(("k0", "k1", "k2")):
        assert rc.put(key, _entry()) == []
        t = time.time() - 100 + 10 * i
        os.utime(store / f"{key}.json", (t, t))
    size = (store / "k2.json").stat().st_size
    bounded = ResultCache(8, cache_dir=str(store), max_bytes=2 * size)
    evicted = bounded.gc()
    assert [e["key"] for e in evicted] == ["k0"]
    assert evicted[0]["reason"] == "max_bytes"
    assert sorted(p.name for p in store.glob("*.json")) == \
        ["k1.json", "k2.json"]
    # already within bounds: the next sweep is a no-op
    assert bounded.gc() == []


def test_disk_gc_never_evicts_the_entry_just_stored(tmp_path):
    probe = ResultCache(8, cache_dir=str(tmp_path / "probe"))
    probe.put("k", _entry())
    size = (tmp_path / "probe" / "k.json").stat().st_size
    store = tmp_path / "store"
    # room for one-and-a-half entries: every store evicts the previous
    # entry, never itself (mtime ordering drops the OLDER entry first)
    rc = ResultCache(8, cache_dir=str(store),
                     max_bytes=size + size // 2)
    assert rc.put("k0", _entry()) == []
    time.sleep(0.02)
    evicted = rc.put("k1", _entry())
    assert [e["key"] for e in evicted] == ["k0"]
    assert (store / "k1.json").exists()


def test_store_time_gc_emits_evict_event(tmp_path):
    """End-to-end: a store whose sweep unlinks a stale disk entry emits
    a schema-valid ``net_cache`` evict event and bumps the counter."""
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    reg = MetricsRegistry()
    store = tmp_path / "rcache"
    cache = ResultCache(32, cache_dir=str(store), ttl_s=60.0)
    front, nf = _stack(tmp_path, logger=logger, cache=cache,
                       registry=reg)
    st, a = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202
    _poll(nf.port, a["ticket"])
    first = next(iter(store.glob("*.json")))
    past = time.time() - 120
    os.utime(first, (past, past))
    st, b = _post(nf.port, "/v1/color", dict(_SPEC, seed=6), tenant="a")
    assert st == 202
    _poll(nf.port, b["ticket"])
    assert not first.exists()
    nf.close()
    front.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_cache"' in ln]
    ev = [r for r in recs if r["action"] == "evict"]
    assert len(ev) == 1
    assert ev[0]["reason"] == "ttl" and ev[0]["bytes"] > 0
    assert ev[0]["key"] == first.name[:-len(".json")]
    snap = reg.to_dict()
    assert snap[
        'dgc_net_cache_disk_evictions_total{reason="ttl"}']["value"] == 1
    assert validate_file(str(log)) == []


# -- recovery-path cache fill -------------------------------------------

def test_recovery_fills_result_cache(tmp_path):
    """A restart's WAL scan inserts every restored delivered record's
    colors into the (empty) result cache: a duplicate of an
    already-computed ticket serves as a hit with ZERO recomputes."""
    front, nf = _stack(tmp_path, cache=ResultCache(32))
    st, doc = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202
    st, res = _poll(nf.port, doc["ticket"])
    assert st == 200 and res["status"] == "ok"
    nf.close()
    front.shutdown()
    # second incarnation: fresh empty cache, same journal dir
    log = tmp_path / "run2.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front2, nf2 = _stack(tmp_path, logger=logger, cache=ResultCache(32))
    st, hit = _post(nf2.port, "/v1/color", dict(_SPEC), tenant="b")
    assert st == 202 and hit["cached"] is True
    st, again = _get(nf2.port, f"/v1/result/{hit['ticket']}?colors=1")
    assert st == 200 and again["colors"] == res["colors"]
    assert front2.computes == 0
    snap = nf2.resultcache.snapshot()
    assert snap["stores"] >= 1 and snap["hits"] == 1
    nf2.close()
    front2.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_cache"' in ln]
    fills = [r for r in recs if r["action"] == "recover_fill"]
    assert len(fills) == 1 and fills[0]["ticket"] == doc["ticket"]
    assert validate_file(str(log)) == []


# -- end-to-end: cache hits over the netfront ---------------------------

def test_cache_hit_serves_byte_identical_colors(tmp_path):
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger, cache=ResultCache(32))
    st, doc = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202 and "cached" not in doc
    st, first = _poll(nf.port, doc["ticket"])
    assert st == 200 and first["status"] == "ok"
    # identical resubmission: acked as a hit, pollable immediately
    st, doc2 = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202 and doc2["cached"] is True and doc2["priority"] == 0
    st, again = _get(nf.port, f"/v1/result/{doc2['ticket']}?colors=1")
    assert st == 200
    assert again["colors"] == first["colors"]
    assert again["minimal_colors"] == first["minimal_colors"]
    assert front.computes == 1
    st, health = _get(nf.port, "/healthz")
    assert health["result_cache"]["hits"] == 1
    assert health["result_cache"]["stores"] == 1
    assert health["result_cache"]["entries"] == 1
    nf.close()
    front.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_cache"' in ln]
    assert [r["action"] for r in recs] == ["miss", "store", "hit"]
    assert recs[-1]["source"] == "mem"
    assert recs[-1]["cached_from"] == doc["ticket"]
    assert validate_file(str(log)) == []


def test_concurrent_identical_hammer_computes_once(tmp_path):
    """The single-flight contract: N concurrent identical submissions,
    exactly ONE compute, N-1 followers coalesced, every ticket served
    the same colors."""
    n = 8
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    gate = threading.Event()
    front, nf = _stack(tmp_path, logger=logger, gate=gate,
                       cache=ResultCache(32))
    st, lead = _post(nf.port, "/v1/color", dict(_SPEC))
    assert st == 202
    tickets, errs = [lead["ticket"]], []

    def submit():
        try:
            st, doc = _post(nf.port, "/v1/color", dict(_SPEC))
            assert st == 202
            tickets.append(doc["ticket"])
        except Exception as e:       # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=submit) for _ in range(n - 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs and len(tickets) == n
    gate.set()
    colors = []
    for t in tickets:
        st, doc = _poll(nf.port, t)
        assert st == 200 and doc["status"] == "ok"
        colors.append(doc["colors"])
    assert all(c == colors[0] for c in colors)
    assert front.computes == 1
    snap = nf.resultcache.snapshot()
    assert snap["coalesced"] == n - 1
    # once the leader published, fresh submissions are plain hits
    st, doc = _post(nf.port, "/v1/color", dict(_SPEC))
    assert st == 202 and doc["cached"] is True
    assert front.computes == 1
    nf.close()
    front.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_cache"' in ln]
    acts = [r["action"] for r in recs]
    assert acts.count("coalesced") == n - 1 and acts.count("miss") == 1
    for r in recs:
        if r["action"] == "coalesced":
            assert r["cached_from"] == lead["ticket"]
    assert validate_file(str(log)) == []


def test_leader_failure_promotes_follower(tmp_path):
    """A follower whose leader dies before computing is promoted to its
    own recompute — an acked ticket is never lost to coalescing."""
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    registry = MetricsRegistry()
    front, nf = _stack(tmp_path, logger=logger, cache=ResultCache(32),
                       registry=registry, front_cls=_WedgeSubmitFront)
    front.arm_failure()
    lead_resp = {}

    def lead():
        lead_resp["st"], lead_resp["doc"] = _post(
            nf.port, "/v1/color", dict(_SPEC))

    t = threading.Thread(target=lead)
    t.start()
    assert front.submit_wedged.wait(30)
    st, fdoc = _post(nf.port, "/v1/color", dict(_SPEC))
    assert st == 202 and "cached" not in fdoc
    front.submit_release.set()
    t.join(30)
    # the leader itself got structured backpressure...
    assert lead_resp["st"] == 429
    assert lead_resp["doc"]["reason"] == "queue_full"
    # ...while the already-acked follower completed via promotion
    st, doc = _poll(nf.port, fdoc["ticket"])
    assert st == 200 and doc["status"] == "ok"
    assert doc["colors"] == [i % 3 for i in range(_SPEC["node_count"])]
    assert front.computes == 1
    st, metrics = _get(nf.port, "/healthz")
    nf.close()
    front.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_cache"' in ln]
    acts = [r["action"] for r in recs]
    assert acts.count("coalesced") == 1 and acts.count("promote") == 1
    promoted = [r for r in recs if r["action"] == "promote"]
    assert promoted[0]["ticket"] == fdoc["ticket"]
    assert validate_file(str(log)) == []


def test_kill_resume_replays_coalesced_group(tmp_path):
    """The crash window for a single-flight group: leader AND follower
    journaled admitted+seated, neither delivered. Recovery replays each
    under its original id as an independent compute — determinism makes
    the two colorings identical, so coalescing never weakens the
    journal's zero-acked-loss contract."""
    j = TicketJournal(str(tmp_path / "journal"))
    j.append("admitted", "t00000000", tenant="x", priority=1,
             payload=dict(_SPEC))
    j.append("seated", "t00000000")
    j.append("admitted", "t00000001", tenant="y", priority=1,
             payload=dict(_SPEC))
    j.append("seated", "t00000001")
    j.close()
    log = tmp_path / "replay.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger, cache=ResultCache(32))
    st, a = _poll(nf.port, "t00000000")
    st2, b = _poll(nf.port, "t00000001")
    assert st == 200 and st2 == 200
    assert a["status"] == "ok" and b["status"] == "ok"
    assert a["colors"] == b["colors"]
    nf.close()
    front.shutdown()
    logger.close()
    recs = [json.loads(ln) for ln in open(log) if '"net_recover"' in ln]
    assert recs[-1]["replayed"] == 2 and recs[-1]["restored"] == 0
    assert validate_file(str(log)) == []


# -- metering -----------------------------------------------------------

def test_usage_isolates_cached_unit_per_tenant(tmp_path):
    """Tenant ``a`` pays for the compute; tenant ``b``'s identical
    submission meters as the cheaper ``cached`` unit — and the cached
    count never leaks into the computing tenant's row."""
    front, nf = _stack(tmp_path, cache=ResultCache(32))
    st, doc = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202
    _poll(nf.port, doc["ticket"])
    st, doc2 = _post(nf.port, "/v1/color", dict(_SPEC), tenant="b")
    assert st == 202 and doc2["cached"] is True
    st, rows = _get(nf.port, "/admin/usage")
    assert st == 200
    by_tenant = {r["tenant"]: r for r in rows["usage"]}
    assert by_tenant["a"]["delivered"] == 1 and "cached" not in by_tenant["a"]
    assert by_tenant["b"]["delivered"] == 1 and by_tenant["b"]["cached"] == 1
    nf.close()
    front.shutdown()


def test_usage_conservation_holds_with_cached_deliveries(tmp_path):
    """Per-tenant usage rows vs the journal's ground truth, with hits
    and coalesced deliveries in the mix: every lifecycle count — and
    the ``cached`` unit — must reconcile exactly."""
    gate = threading.Event()
    front, nf = _stack(tmp_path, gate=gate, cache=ResultCache(32))
    st, lead = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202
    st, fol = _post(nf.port, "/v1/color", dict(_SPEC), tenant="b")
    assert st == 202
    gate.set()
    _poll(nf.port, lead["ticket"])
    _poll(nf.port, fol["ticket"])
    st, hit = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
    assert st == 202 and hit["cached"] is True
    st, rows = _get(nf.port, "/admin/usage")
    jpath = nf.journal.path
    nf.close()
    front.shutdown()
    totals = journal_totals(jpath)
    assert totals["admitted"] == 3 and totals["delivered"] == 3
    assert totals["cached"] == 2
    assert conservation_problems(rows["usage"], jpath) == []


# -- the off switch -----------------------------------------------------

def test_cache_off_is_byte_identical(tmp_path):
    """``resultcache=None`` (the default) must reproduce the PR 17
    surface exactly: no ``net_cache`` events, no ``cached`` fields in
    acks or usage rows, no ``result_cache`` health block, and every
    identical submission pays its own compute."""
    log = tmp_path / "run.jsonl"
    logger = RunLogger(jsonl_path=str(log), echo=False)
    front, nf = _stack(tmp_path, logger=logger)
    for _ in range(2):
        st, doc = _post(nf.port, "/v1/color", dict(_SPEC), tenant="a")
        assert st == 202 and "cached" not in doc
        st, res = _poll(nf.port, doc["ticket"])
        assert st == 200 and "cached" not in res
    assert front.computes == 2
    st, health = _get(nf.port, "/healthz")
    assert "result_cache" not in health
    st, rows = _get(nf.port, "/admin/usage")
    assert all("cached" not in r for r in rows["usage"])
    jpath = nf.journal.path
    nf.close()
    front.shutdown()
    logger.close()
    assert not any('"net_cache"' in ln for ln in open(log))
    assert journal_totals(jpath)["cached"] == 0
    assert validate_file(str(log)) == []


# -- tuned-config exact-hash fast path ----------------------------------

def test_tuned_cache_exact_hash_skips_shape_pass(tmp_path):
    arrays = Graph.generate(48, 4, seed=3, method="fast").arrays
    other = Graph.generate(96, 6, seed=9, method="fast").arrays
    cache = TunedConfigCache()
    cfg = TunedConfig(prune_u_div=8,
                      graph_shape_hash=graph_shape_hash(arrays))
    cache.put(arrays, cfg, content_hash="ck")
    # the exact hit returns without computing the shape hash at all:
    # content-identity pins the config even when the passed arrays
    # would shape-hash elsewhere
    got = cache.get(other, content_hash="ck")
    assert got is cfg and cache.stats["exact_hits"] == 1
    assert cache.stats["hits"] == 0


def test_tuned_cache_hash_mismatch_falls_back_to_shape(tmp_path):
    """The regression the fast path must not introduce: an unknown
    content hash (same shape, different exact graph) degrades to the
    shape-hash lookup — never a miss, never a wrong config — and the
    fallback binds the new hash for next time."""
    arrays = Graph.generate(48, 4, seed=3, method="fast").arrays
    cache = TunedConfigCache()
    cfg = TunedConfig(prune_u_div=8,
                      graph_shape_hash=graph_shape_hash(arrays))
    cache.put(arrays, cfg)
    got = cache.get(arrays, content_hash="unseen")
    assert got is cfg
    assert cache.stats["hits"] == 1 and cache.stats["exact_hits"] == 0
    # ...and the miss remembered the binding: same hash now exact-hits
    got = cache.get(arrays, content_hash="unseen")
    assert got is cfg and cache.stats["exact_hits"] == 1


def test_tuned_cache_exact_binding_survives_disk_reload(tmp_path):
    arrays = Graph.generate(48, 4, seed=3, method="fast").arrays
    shape = graph_shape_hash(arrays)
    warm = TunedConfigCache(cache_dir=str(tmp_path / "tuned"))
    warm.put(arrays, TunedConfig(prune_u_div=8, graph_shape_hash=shape))
    cold = TunedConfigCache(cache_dir=str(tmp_path / "tuned"))
    got = cold.get(arrays, content_hash="ck")
    assert got is not None and cold.stats["disk_hits"] == 1
    assert cold.get(arrays, content_hash="ck") is got
    assert cold.stats["exact_hits"] == 1

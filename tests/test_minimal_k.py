"""Outer-loop tests: jump vs strict schedules, checkpoint/resume, quirk fix."""

import numpy as np

from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.generators import generate_random_graph
from dgc_tpu.ops.validate import validate_coloring
from dgc_tpu.utils.checkpoint import CheckpointManager


def test_jump_and_strict_agree(small_graphs):
    for g in small_graphs:
        k0 = g.max_degree + 1
        jump = find_minimal_coloring(ELLEngine(g), k0)
        strict = find_minimal_coloring(ELLEngine(g), k0, strict_decrement=True)
        assert jump.minimal_colors == strict.minimal_colors
        # jump mode: exactly 2 attempts (find u, confirm u−1 fails), unless u == k0
        assert len(jump.attempts) <= 3
        # strict mode mirrors the reference's one-by-one schedule
        # (coloring.py:226-231): k0 − u + 2 attempts (final one fails)
        assert len(strict.attempts) == k0 - strict.minimal_colors + 2 - (
            1 if strict.minimal_colors == 1 else 0
        )


def test_last_valid_coloring_kept(small_graphs):
    # the reference saves the failed attempt's partial coloring
    # (SURVEY §3.1); we must return the last *valid* one
    g = small_graphs[0]
    res = find_minimal_coloring(ELLEngine(g), g.max_degree + 1)
    assert (res.colors >= 0).all()
    assert validate_coloring(g.indptr, g.indices, res.colors).valid
    assert not res.attempts[-1].success  # sweep ends on the failing attempt
    assert res.minimal_colors == res.attempts[-2].colors_used


def test_checkpoint_resume(tmp_path):
    g = generate_random_graph(120, 8, seed=5)
    k0 = g.max_degree + 1
    full = find_minimal_coloring(ELLEngine(g), k0, strict_decrement=True)

    # run once with checkpointing, interrupting after the second attempt
    class Interrupt(Exception):
        pass

    ckpt = CheckpointManager(tmp_path / "ck")
    count = 0

    def boom(res, val):
        nonlocal count
        count += 1
        if count == 2:
            raise Interrupt

    try:
        find_minimal_coloring(
            ELLEngine(g), k0, strict_decrement=True, on_attempt=boom, checkpoint=ckpt
        )
    except Interrupt:
        pass

    resumed = find_minimal_coloring(
        ELLEngine(g), k0, strict_decrement=True, checkpoint=ckpt
    )
    assert resumed.minimal_colors == full.minimal_colors
    assert validate_coloring(g.indptr, g.indices, resumed.colors).valid
    # resumed run skips the attempts done before the interrupt
    assert len(resumed.attempts) < len(full.attempts) + 1


def test_checkpoint_resume_after_done(tmp_path):
    g = generate_random_graph(50, 5, seed=9)
    ckpt = CheckpointManager(tmp_path / "ck2")
    first = find_minimal_coloring(ELLEngine(g), g.max_degree + 1, checkpoint=ckpt)
    again = find_minimal_coloring(ELLEngine(g), g.max_degree + 1, checkpoint=ckpt)
    assert again.minimal_colors == first.minimal_colors
    assert len(again.attempts) == 1  # only the restored best; no re-execution

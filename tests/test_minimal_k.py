"""Outer-loop tests: jump vs strict schedules, checkpoint/resume, quirk fix."""

import numpy as np

from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.generators import generate_random_graph
from dgc_tpu.ops.validate import validate_coloring
from dgc_tpu.utils.checkpoint import CheckpointManager


def test_jump_and_strict_agree(small_graphs):
    for g in small_graphs:
        k0 = g.max_degree + 1
        jump = find_minimal_coloring(ELLEngine(g), k0)
        strict = find_minimal_coloring(ELLEngine(g), k0, strict_decrement=True)
        assert jump.minimal_colors == strict.minimal_colors
        # jump mode: exactly 2 attempts (find u, confirm u−1 fails), unless u == k0
        assert len(jump.attempts) <= 3
        # strict mode mirrors the reference's one-by-one schedule
        # (coloring.py:226-231): k0 − u + 2 attempts (final one fails)
        assert len(strict.attempts) == k0 - strict.minimal_colors + 2 - (
            1 if strict.minimal_colors == 1 else 0
        )


def test_last_valid_coloring_kept(small_graphs):
    # the reference saves the failed attempt's partial coloring
    # (SURVEY §3.1); we must return the last *valid* one
    g = small_graphs[0]
    res = find_minimal_coloring(ELLEngine(g), g.max_degree + 1)
    assert (res.colors >= 0).all()
    assert validate_coloring(g.indptr, g.indices, res.colors).valid
    assert not res.attempts[-1].success  # sweep ends on the failing attempt
    assert res.minimal_colors == res.attempts[-2].colors_used


def test_checkpoint_resume(tmp_path):
    g = generate_random_graph(120, 8, seed=5)
    k0 = g.max_degree + 1
    full = find_minimal_coloring(ELLEngine(g), k0, strict_decrement=True)

    # run once with checkpointing, interrupting after the second attempt
    class Interrupt(Exception):
        pass

    ckpt = CheckpointManager(tmp_path / "ck")
    count = 0

    def boom(res, val):
        nonlocal count
        count += 1
        if count == 2:
            raise Interrupt

    try:
        find_minimal_coloring(
            ELLEngine(g), k0, strict_decrement=True, on_attempt=boom, checkpoint=ckpt
        )
    except Interrupt:
        pass

    resumed = find_minimal_coloring(
        ELLEngine(g), k0, strict_decrement=True, checkpoint=ckpt
    )
    assert resumed.minimal_colors == full.minimal_colors
    assert validate_coloring(g.indptr, g.indices, resumed.colors).valid
    # resumed run skips the attempts done before the interrupt
    assert len(resumed.attempts) < len(full.attempts) + 1


def test_checkpoint_resume_after_done(tmp_path):
    g = generate_random_graph(50, 5, seed=9)
    ckpt = CheckpointManager(tmp_path / "ck2")
    first = find_minimal_coloring(ELLEngine(g), g.max_degree + 1, checkpoint=ckpt)
    again = find_minimal_coloring(ELLEngine(g), g.max_degree + 1, checkpoint=ckpt)
    assert again.minimal_colors == first.minimal_colors
    assert len(again.attempts) == 1  # only the restored best; no re-execution


def _seq(result):
    return [(a.k, a.status, a.colors_used) for a in result.attempts]


class _NoSweep:
    """Strips sweep() so find_minimal_coloring takes the per-attempt loop —
    the equivalence oracle for the fused path."""

    def __init__(self, engine):
        self._engine = engine

    def attempt(self, k):
        return self._engine.attempt(k)


def test_fused_sweep_with_checkpoint(tmp_path):
    # checkpointing must no longer forfeit the fused sweep (round-3 Weak #6):
    # same attempt sequence as the uncheckpointed fused run, and a completed
    # checkpoint short-circuits re-execution
    from dgc_tpu.engine.compact import CompactFrontierEngine

    g = generate_random_graph(300, 8, seed=11)
    k0 = g.max_degree + 1
    plain = find_minimal_coloring(CompactFrontierEngine(g), k0)
    assert len(plain.attempts) == 2  # the fused pair ran

    ckpt = CheckpointManager(tmp_path / "ckf")
    ck_run = find_minimal_coloring(CompactFrontierEngine(g), k0, checkpoint=ckpt)
    assert _seq(ck_run) == _seq(plain)
    assert ck_run.minimal_colors == plain.minimal_colors

    resumed = find_minimal_coloring(CompactFrontierEngine(g), k0, checkpoint=ckpt)
    assert resumed.minimal_colors == plain.minimal_colors
    assert len(resumed.attempts) == 1  # restored best only; no re-execution


def test_fused_sweep_checkpoint_mid_pair_resume(tmp_path):
    # interrupt after the pair's FIRST half; the resumed run re-enters via
    # sweep(next_k) and the combined sequence matches an uninterrupted run
    from dgc_tpu.engine.compact import CompactFrontierEngine

    g = generate_random_graph(300, 8, seed=12)
    k0 = g.max_degree + 1
    plain = find_minimal_coloring(CompactFrontierEngine(g), k0)

    class Interrupt(Exception):
        pass

    count = 0

    def boom(res, val):
        # on_attempt fires BEFORE checkpoint.save, so raising on the pair's
        # second half leaves exactly the first half saved — the mid-pair state
        nonlocal count
        count += 1
        if count == 2:
            raise Interrupt

    ckpt = CheckpointManager(tmp_path / "ckm")
    try:
        find_minimal_coloring(CompactFrontierEngine(g), k0,
                              on_attempt=boom, checkpoint=ckpt)
    except Interrupt:
        pass

    restored = ckpt.restore()
    assert restored is not None and not restored[2]  # mid-pair: not done
    assert restored[0] == plain.attempts[0].colors_used - 1  # resumes at confirm k

    resumed = find_minimal_coloring(CompactFrontierEngine(g), k0, checkpoint=ckpt)
    assert resumed.minimal_colors == plain.minimal_colors
    # restored best (the first half) + the re-swept confirm tail
    assert len(resumed.attempts) == 2
    assert _seq(resumed) == _seq(plain)
    assert validate_coloring(g.indptr, g.indices, resumed.colors).valid


def test_fused_k_min_matches_per_attempt_loop():
    # a raised k_min floor must not forfeit the fused sweep; the pair's
    # sub-floor confirm attempt is dropped — exactly what the per-attempt
    # loop never executes
    from dgc_tpu.engine.compact import CompactFrontierEngine

    g = generate_random_graph(300, 8, seed=13)
    k0 = g.max_degree + 1
    m = find_minimal_coloring(CompactFrontierEngine(g), k0).minimal_colors
    for k_min in (1, m, m + 2):
        fused = find_minimal_coloring(CompactFrontierEngine(g), k0, k_min=k_min)
        loop = find_minimal_coloring(_NoSweep(CompactFrontierEngine(g)), k0,
                                     k_min=k_min)
        assert _seq(fused) == _seq(loop), k_min
        assert fused.minimal_colors == loop.minimal_colors, k_min

"""Mid-sweep kill-and-resume coverage (resilience satellite).

Interrupts a checkpointed sweep at *every* attempt boundary — via an
injected kill at the ``checkpoint_write`` fault point, i.e. immediately
after each attempt's state lands on disk — and asserts the resumed run
executes exactly the attempts the uninterrupted run would have executed
after that boundary, with bit-identical final colors. Covers jump mode,
strict mode, and the fused-pair engine (where the boundary after the
pair's first half is the mid-fused-pair state ``minimal_k.py:82-101``
documents)."""

import numpy as np
import pytest

from dgc_tpu.engine.minimal_k import find_minimal_coloring
from dgc_tpu.engine.superstep import ELLEngine
from dgc_tpu.models.generators import generate_random_graph
from dgc_tpu.ops.validate import validate_coloring
from dgc_tpu.resilience import faults
from dgc_tpu.resilience.faults import (FaultPlane, FaultSchedule,
                                       SimulatedKill)
from dgc_tpu.utils.checkpoint import CheckpointManager

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leaked_plane():
    yield
    faults.uninstall()


def _engine(g, fused: bool):
    if fused:
        from dgc_tpu.engine.compact import CompactFrontierEngine

        return CompactFrontierEngine(g)
    return ELLEngine(g)


def _seq(attempts):
    return [(a.k, int(a.status), a.colors_used if a.success else None)
            for a in attempts]


def _run_with_kill_at(g, k0, boundary: int, *, strict: bool, fused: bool,
                      ckpt_dir):
    """One sweep killed right after attempt #``boundary`` checkpoints."""
    executed = []
    ckpt = CheckpointManager(ckpt_dir, fingerprint="fp")
    plane = FaultPlane(
        FaultSchedule.parse(f"checkpoint_write@{boundary}=kill"),
        hard_kill=False)
    with faults.injected(plane):
        with pytest.raises(SimulatedKill):
            find_minimal_coloring(
                _engine(g, fused), k0, strict_decrement=strict,
                on_attempt=lambda res, val: executed.append(res),
                checkpoint=ckpt)
    return executed, ckpt


@pytest.mark.parametrize("strict,fused", [
    (False, False),   # jump mode, per-attempt engine
    (True, False),    # strict (reference) schedule
    (False, True),    # jump mode, fused sweep() pair — incl. mid-pair kill
])
def test_kill_at_every_attempt_boundary_resumes_bit_identical(
        tmp_path, strict, fused):
    g = generate_random_graph(150, 8, seed=21)
    k0 = g.max_degree + 1
    full_executed = []
    full = find_minimal_coloring(
        _engine(g, fused), k0, strict_decrement=strict,
        on_attempt=lambda res, val: full_executed.append(res))
    n_attempts = len(full.attempts)
    assert n_attempts >= 2

    for boundary in range(1, n_attempts + 1):
        pre, ckpt = _run_with_kill_at(
            g, k0, boundary, strict=strict, fused=fused,
            ckpt_dir=tmp_path / f"{strict}-{fused}-{boundary}")
        assert len(pre) == boundary  # killed exactly at that boundary

        resumed_executed = []
        resumed = find_minimal_coloring(
            _engine(g, fused), k0, strict_decrement=strict,
            on_attempt=lambda res, val: resumed_executed.append(res),
            checkpoint=ckpt)

        # the combined executed-attempt sequence is exactly the
        # uninterrupted run's sequence (the restored best is replayed
        # into results but never re-executed, so it is not in either list)
        assert _seq(pre) + _seq(resumed_executed) == _seq(full_executed), \
            (strict, fused, boundary)
        assert resumed.minimal_colors == full.minimal_colors
        assert np.array_equal(resumed.colors, full.colors)
        assert validate_coloring(g.indptr, g.indices, resumed.colors).valid


def test_mid_fused_pair_state_is_the_documented_one(tmp_path):
    # kill after the fused pair's FIRST half: the checkpoint must hold
    # next_k = colors_used - 1 and not-done — the mid-pair resume state
    # minimal_k.py documents; the resumed run re-sweeps from there
    from dgc_tpu.engine.compact import CompactFrontierEngine

    g = generate_random_graph(150, 8, seed=22)
    k0 = g.max_degree + 1
    full = find_minimal_coloring(CompactFrontierEngine(g), k0)
    assert len(full.attempts) == 2  # the fused pair ran

    pre, ckpt = _run_with_kill_at(g, k0, 1, strict=False, fused=True,
                                  ckpt_dir=tmp_path / "midpair")
    restored = ckpt.restore()
    assert restored is not None
    next_k, best, done = restored
    assert not done
    assert next_k == pre[0].colors_used - 1
    assert np.array_equal(best.colors, pre[0].colors)

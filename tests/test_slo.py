"""SLO gate (tools/slo_check.py): percentile estimator, the
manifest-based gate (pass / injected violation → nonzero), the bench
tripwire rule, and the metrics-histogram fallback. Tier-1 smoke."""

import json
import sys

import numpy as np
import pytest

sys.path.insert(0, "tools")

import slo_check  # noqa: E402


def test_percentile_matches_numpy_linear_interpolation():
    rng = np.random.default_rng(7)
    for n in (1, 2, 5, 100):
        xs = rng.uniform(0, 500, size=n).tolist()
        for q in (0.0, 0.5, 0.95, 0.99, 1.0):
            got = slo_check.percentile(xs, q)
            want = float(np.percentile(xs, q * 100))
            assert got == pytest.approx(want, rel=1e-12), (n, q)
    assert slo_check.percentile([], 0.5) is None


def _serve_doc(service_ms, queue_ms=None, cls="v2048w16",
               gps=1.5, failed=0):
    queue_ms = queue_ms if queue_ms is not None else [1.0] * len(service_ms)
    reqs = [{"request_id": i, "status": "ok", "service_ms": s,
             "queue_ms": q, "shape_class": cls}
            for i, (s, q) in enumerate(zip(service_ms, queue_ms))]
    return {
        "manifest_version": 1,
        "serve": {
            "requests": reqs,
            "summary": {"requests": len(reqs), "completed": len(reqs) - failed,
                        "failed": failed, "graphs_per_s": gps},
        },
    }


def test_check_serve_doc_passes_and_catches_violations():
    doc = _serve_doc([10.0, 20.0, 30.0, 40.0], gps=2.0)
    ok = {"service_ms": {"p50": 100}, "queue_ms": {"p95": 50},
          "graphs_per_s_min": 1.0, "failure_rate_max": 0.0}
    assert slo_check.check_serve_doc(doc, ok) == []

    # p95 violation
    v = slo_check.check_serve_doc(doc, {"service_ms": {"p95": 20}})
    assert len(v) == 1 and "p95" in v[0] and "service_ms" in v[0]

    # throughput + failure-rate violations
    doc_bad = _serve_doc([10.0], gps=0.2, failed=1)
    v = slo_check.check_serve_doc(
        doc_bad, {"graphs_per_s_min": 1.0, "failure_rate_max": 0.0})
    assert any("throughput" in x for x in v)
    assert any("failure rate" in x for x in v)

    # per-class gate only sees its class
    doc2 = _serve_doc([500.0] * 4, cls="v8192w64")
    v = slo_check.check_serve_doc(
        doc2, {"classes": {"v8192w64": {"service_ms": {"p50": 100}}}})
    assert len(v) == 1 and "class v8192w64" in v[0]
    assert slo_check.check_serve_doc(
        doc2, {"classes": {"v2048w16": {"service_ms": {"p50": 100}}}})
    # (thresholds over a class with no samples are themselves a finding)

    # unknown quantile names are reported, not silently skipped
    v = slo_check.check_serve_doc(doc, {"service_ms": {"p42": 1}})
    assert any("unknown quantile" in x for x in v)


def test_histogram_fallback_when_no_request_list():
    # manifest without serve.requests: gate over the metrics snapshot's
    # bucket counts (bucket-midpoint expansion)
    doc = {
        "manifest_version": 1,
        "serve": {"requests": [], "summary": {}},
        "metrics": {
            'dgc_serve_service_seconds{shape_class="v2048w16"}': {
                "kind": "histogram", "sum": 1.0, "count": 4,
                "buckets": {"0.01": 2, "0.1": 2}, "inf": 0},
        },
    }
    assert slo_check.check_serve_doc(doc, {"service_ms": {"p95": 100}}) == []
    v = slo_check.check_serve_doc(doc, {"service_ms": {"p95": 20}})
    assert len(v) == 1 and "p95" in v[0]


def test_slo_check_cli_gate(tmp_path, capsys):
    """The tier-1 smoke the ISSUE asks for: clean run passes (rc 0), an
    injected violation exits nonzero (rc 1), bad inputs rc 2."""
    manifest = tmp_path / "run.json"
    manifest.write_text(json.dumps(_serve_doc([10.0, 15.0, 20.0], gps=3.0)))

    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({"service_ms": {"p99": 100},
                              "graphs_per_s_min": 1.0}))
    assert slo_check.main([str(manifest), "--thresholds", str(ok)]) == 0
    assert "SLO PASS" in capsys.readouterr().out

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"service_ms": {"p50": 5},
                               "graphs_per_s_min": 99.0}))
    assert slo_check.main([str(manifest), "--thresholds", str(bad)]) == 1
    err = capsys.readouterr().err
    assert err.count("SLO VIOLATION") == 2

    assert slo_check.main([str(tmp_path / "missing.json"),
                           "--thresholds", str(ok)]) == 2
    notjson = tmp_path / "notjson"
    notjson.write_text("[1, 2]")
    assert slo_check.main([str(manifest), "--thresholds",
                           str(notjson)]) == 2


def test_slo_check_reads_jsonl_runlog(tmp_path):
    # the JSONL form replays through RunManifest (report_run convention),
    # tolerating a torn tail
    log = tmp_path / "run.jsonl"
    events = [
        {"t": 0.1, "event": "serve_request", "request_id": 1,
         "status": "ok", "queue_ms": 1.0, "service_ms": 12.0,
         "shape_class": "v2048w16"},
        {"t": 0.2, "event": "serve_summary", "requests": 1, "completed": 1,
         "failed": 0, "wall_s": 0.5, "graphs_per_s": 2.0},
    ]
    log.write_text("\n".join(json.dumps(e) for e in events)
                   + "\n" + '{"torn')
    th = tmp_path / "th.json"
    th.write_text(json.dumps({"service_ms": {"p50": 100},
                              "graphs_per_s_min": 1.0}))
    assert slo_check.main([str(log), "--thresholds", str(th)]) == 0
    th.write_text(json.dumps({"graphs_per_s_min": 10.0}))
    assert slo_check.main([str(log), "--thresholds", str(th)]) == 1


def test_check_bench_record_tripwire():
    rec = {"value": 1.2, "speedup_vs_sequential": 6.5}
    assert slo_check.check_bench_record(
        rec, {"graphs_per_s_min": 1.0,
              "speedup_vs_sequential_min": 3.0}) == []
    v = slo_check.check_bench_record(
        rec, {"graphs_per_s_min": 2.0, "speedup_vs_sequential_min": 8.0})
    assert len(v) == 2
    v = slo_check.check_bench_record(
        {"value": 1.0}, {"speedup_vs_sequential_min": 3.0})
    assert len(v) == 1 and "no speedup" in v[0]

"""Guard: the test environment must provide the 8-device virtual CPU mesh
(SURVEY.md §7.2 step 5). If this fails, multi-device tests are vacuous."""

import os

import jax


def test_eight_cpu_devices():
    import pytest

    if os.environ.get("DGC_TPU_TEST_ON_TPU") == "1":
        pytest.skip("running on real TPU hardware by request")
    if jax.local_device_count() < 8:
        # conftest forces --xla_force_host_platform_device_count=8 (and
        # re-execs once if jax arrived pre-imported); landing here means
        # some embedding process pinned a backend before either lever
        # could act — the multi-device families skip on their own guards
        pytest.skip("8-device forcing impossible (jax pre-imported with "
                    "a pinned backend); multi-device tests skip cleanly")
    assert jax.devices()[0].platform == "cpu"
    assert jax.local_device_count() == 8

"""Guard: the test environment must provide the 8-device virtual CPU mesh
(SURVEY.md §7.2 step 5). If this fails, multi-device tests are vacuous."""

import os

import jax


def test_eight_cpu_devices():
    if os.environ.get("DGC_TPU_TEST_ON_TPU") == "1":
        import pytest

        pytest.skip("running on real TPU hardware by request")
    assert jax.devices()[0].platform == "cpu"
    assert jax.local_device_count() == 8

"""Serve-tier fault plane (crash-safe serve PR): one tier-1 test per
new injection point — ``serve_dispatch`` / ``lane_seat`` / ``deliver``
/ ``journal_write`` / ``net_accept`` — each asserting the
recover-or-structured-abort contract under a seeded ``--inject-faults``
spec, plus the quarantine and dispatch-watchdog policies, the sync-mode
requeue path, and a subprocess run of the serve CLI with the flag.

The journal-side points (``journal_write`` / ``net_accept``) and the
kill-at-journal-boundary resume sweep live in ``tests/test_journal.py``
beside the journal they exercise."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dgc_tpu.models.graph import Graph
from dgc_tpu.resilience import faults
from dgc_tpu.serve.queue import ServeFrontEnd
from tools.validate_runlog import validate_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.chaos, pytest.mark.serve]


@pytest.fixture(scope="module")
def graph():
    # 400 vertices lands in the batched shape ladder (v2048 class), so
    # the dispatch/seat points are on the real batched path
    return Graph.generate(400, 5, seed=3, method="fast")


@pytest.fixture(scope="module")
def front(graph, tmp_path_factory):
    log = tmp_path_factory.mktemp("chaos_serve") / "run.jsonl"
    from dgc_tpu.obs import RunLogger

    logger = RunLogger(jsonl_path=str(log), echo=False)
    fe = ServeFrontEnd(batch_max=2, workers=2, queue_depth=16,
                       window_s=0.0, dispatch_timeout=4.0,
                       max_lane_aborts=3, logger=logger).start()
    fe._test_log = str(log)
    # warm the class so the per-test sweeps measure faults, not compiles
    r = fe.submit(graph.arrays).result(timeout=300)
    assert r.status == "ok" and r.batched
    fe._baseline_colors = np.asarray(r.colors).tolist()
    yield fe
    fe.shutdown()
    logger.close()
    assert validate_file(str(log)) == []


def _sweep(front, graph, spec):
    plane = faults.FaultPlane(faults.FaultSchedule.parse(spec))
    with faults.injected(plane):
        res = front.submit(graph.arrays).result(timeout=300)
    return res, plane.fired_snapshot()


def _rebuild_events(front):
    return [json.loads(ln) for ln in open(front._test_log)
            if '"lane_rebuild"' in ln]


def test_serve_dispatch_transient_recovers_bit_identical(front, graph):
    before = len(_rebuild_events(front))
    res, fired = _sweep(front, graph, "serve_dispatch@1=transient")
    assert fired and res.status == "ok" and res.batched
    # recovery is invisible in the output: the reseated sweep restarts
    # from its inputs and the kernel is deterministic
    assert np.asarray(res.colors).tolist() == front._baseline_colors
    events = _rebuild_events(front)[before:]
    assert events and events[0]["reason"] == "abort"
    assert events[0]["reseated"] == 1 and events[0]["quarantined"] == 0


def test_serve_dispatch_poison_quarantined_with_rc(front, graph):
    res, fired = _sweep(
        front, graph,
        "serve_dispatch@1=transient,serve_dispatch@2=oom,"
        "serve_dispatch@3=fatal")
    assert len(fired) == 3
    assert res.status == "error"
    assert "quarantined" in res.error and "rc 114" in res.error
    events = _rebuild_events(front)
    assert any(e["quarantined"] == 1 for e in events)


def test_serve_dispatch_hang_watchdog_rebuilds(front, graph):
    t0 = time.perf_counter()
    res, fired = _sweep(front, graph, "serve_dispatch@1=hang:30")
    wall = time.perf_counter() - t0
    assert fired and res.status == "ok"
    # the 30s injected hang was cut at the 4s watchdog deadline
    assert wall < 25.0
    assert any(e["reason"] == "hang" for e in _rebuild_events(front))
    assert np.asarray(res.colors).tolist() == front._baseline_colors


def test_lane_seat_fault_retries_then_serves(front, graph):
    res, fired = _sweep(front, graph, "lane_seat@1=oom")
    assert fired and res.status == "ok"
    assert np.asarray(res.colors).tolist() == front._baseline_colors


def test_deliver_fault_structured_fails_one_request(front, graph):
    res, fired = _sweep(front, graph, "deliver@1=transient")
    assert fired and res.status == "error"
    assert "delivery aborted" in res.error and "rc 114" in res.error
    # the worker survived: the next request serves clean
    res2 = front.submit(graph.arrays).result(timeout=300)
    assert res2.status == "ok"
    assert np.asarray(res2.colors).tolist() == front._baseline_colors


def test_quarantine_stats_and_config_validation(front):
    st = front.scheduler.stats_snapshot()
    assert st["rebuilds"] >= 1 and st["quarantined"] >= 1
    from dgc_tpu.serve.engine import BatchScheduler

    with pytest.raises(ValueError):
        BatchScheduler(max_lane_aborts=0)
    with pytest.raises(ValueError):
        BatchScheduler(dispatch_timeout_s=-1.0)


def test_sync_mode_dispatch_fault_requeues(graph):
    """The sync (batch-complete) loop shares the quarantine policy:
    a failed pair dispatch requeues survivors at the head."""
    fe = ServeFrontEnd(batch_max=2, workers=2, queue_depth=8,
                       window_s=0.0, mode="sync",
                       max_lane_aborts=3).start()
    try:
        plane = faults.FaultPlane(
            faults.FaultSchedule.parse("serve_dispatch@1=transient"))
        with faults.injected(plane):
            res = fe.submit(graph.arrays).result(timeout=300)
        assert plane.fired_snapshot() and res.status == "ok"
    finally:
        fe.shutdown()


@pytest.mark.slow
def test_chaos_serve_harness_smoke(tmp_path):
    """End-to-end harness smoke: 2 seeded schedules + 1 SIGKILL/resume
    cycle must exit 0 with a well-formed report (the ci_checks.sh gate
    runs the slightly larger 3+1 version)."""
    report = tmp_path / "chaos_serve.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_serve.py"),
         "--schedules", "2", "--kills", "1", "--clients", "2",
         "--requests-per-client", "2", "--nodes", "400", "--degree", "5",
         "--deadline", "240", "--report", str(report)],
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        cwd=REPO, capture_output=True, text=True, timeout=560,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["chaos_serve"]["failed"] == 0

    from tools.chaos_serve import validate_chaos_serve_report

    doc = json.loads(report.read_text())
    assert validate_chaos_serve_report(doc) == []
    assert doc["kill_resume"]["outcome"] == "ok"
    assert doc["kill_resume"]["kills"] >= 1


def test_serve_cli_inject_faults_flag(tmp_path):
    """The serve CLI's --inject-faults end to end (replay mode): a
    deliver fault structured-fails its request, the fault lands in the
    run log as fault_injected, and the log schema-validates."""
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text(
        '{"id": 1, "node_count": 30, "max_degree": 3, "seed": 1}\n'
        '{"id": 2, "node_count": 30, "max_degree": 3, "seed": 2}\n')
    results = tmp_path / "results.jsonl"
    log = tmp_path / "run.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "dgc_tpu.cli", "serve",
         "--requests", str(reqs), "--results", str(results),
         "--log-json", str(log), "--batch-max", "2",
         "--inject-faults", "deliver@1=transient"],
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 1, (r.stdout, r.stderr)   # one failed request
    rows = [json.loads(ln) for ln in results.read_text().splitlines()]
    failed = [row for row in rows if row["status"] != "ok"]
    assert len(failed) == 1
    assert "rc 114" in failed[0]["error"]
    assert sum(1 for row in rows if row["status"] == "ok") == 1
    log_lines = log.read_text()
    assert '"fault_injected"' in log_lines
    assert validate_file(str(log)) == []


def test_bad_inject_faults_spec_exits_2(tmp_path):
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text('{"id": 1, "node_count": 10, "max_degree": 2}\n')
    r = subprocess.run(
        [sys.executable, "-m", "dgc_tpu.cli", "serve",
         "--requests", str(reqs), "--inject-faults", "nonsense"],
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 2
    assert "inject-faults" in r.stderr

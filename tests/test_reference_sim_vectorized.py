"""Bit-identity of the vectorized reference-sim against the loop form.

The loop form is semantics-by-construction (every statement cites a
reference line); the vectorized form exists so 100k-vertex parity
ensembles are routine. They must agree decision-for-decision: same
status, same superstep count, same colors array — across variants,
k values (including failing ones), and graph families.
"""

import numpy as np
import pytest

from dgc_tpu.engine.base import AttemptStatus
from dgc_tpu.engine.reference_sim import ReferenceSimEngine
from dgc_tpu.models.generators import (generate_random_graph,
                                       generate_rmat_graph)
from dgc_tpu.ops.validate import validate_coloring


def _both(arrays, variant, k, max_supersteps=None):
    loop = ReferenceSimEngine(arrays, variant=variant, impl="loop",
                              max_supersteps=max_supersteps).attempt(k)
    vec = ReferenceSimEngine(arrays, variant=variant, impl="vectorized",
                             max_supersteps=max_supersteps).attempt(k)
    assert vec.status == loop.status, (variant, k, vec.status, loop.status)
    assert vec.supersteps == loop.supersteps, (variant, k)
    assert np.array_equal(vec.colors, loop.colors), (variant, k)
    return loop


@pytest.mark.parametrize("variant", ["optimized", "baseline"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 7])
def test_identical_on_random_graphs(variant, seed):
    arrays = generate_random_graph(80, 8, seed=seed)
    k0 = arrays.max_degree + 1
    res = _both(arrays, variant, k0)
    if res.status == AttemptStatus.SUCCESS:
        assert validate_coloring(arrays.indptr, arrays.indices, res.colors).valid
        # walk k down through success into failure territory
        for k in range(res.colors_used, max(res.colors_used - 3, 1) - 1, -1):
            _both(arrays, variant, k)


@pytest.mark.parametrize("variant", ["optimized", "baseline"])
def test_identical_on_heavy_tail(variant):
    arrays = generate_rmat_graph(600, avg_degree=6, seed=5, native=False)
    k0 = arrays.max_degree + 1
    res = _both(arrays, variant, k0, max_supersteps=3 * 600)
    if res.status == AttemptStatus.SUCCESS:
        _both(arrays, variant, max(res.colors_used - 1, 1),
              max_supersteps=3 * 600)


def test_identical_on_disconnected_graph():
    # several components: the baseline's deferral/stall behavior and the
    # optimized variant's eager color-0 must both match the loop form
    arrays = generate_random_graph(60, 2, seed=11)
    for variant in ("optimized", "baseline"):
        _both(arrays, variant, arrays.max_degree + 1, max_supersteps=200)


def test_identical_under_superstep_cap():
    arrays = generate_random_graph(50, 5, seed=4)
    for variant in ("optimized", "baseline"):
        _both(arrays, variant, arrays.max_degree + 1, max_supersteps=2)


def test_sequential_finish_matches_fixpoint():
    # force the fallback by dropping the round cap via monkeypatch-free
    # route: a long path graph with monotonically increasing priority is
    # the adversarial chain; rounds > 64 engages _sequential_finish
    n = 200
    indptr = np.zeros(n + 1, dtype=np.int32)
    deg = np.full(n, 2, dtype=np.int32)
    deg[0] = deg[-1] = 1
    indptr[1:] = np.cumsum(deg)
    indices = np.empty(indptr[-1], dtype=np.int32)
    for u in range(n):
        nb = [u - 1, u + 1]
        nb = [w for w in nb if 0 <= w < n]
        indices[indptr[u]: indptr[u + 1]] = nb
    from dgc_tpu.models.arrays import GraphArrays

    arrays = GraphArrays(indptr=indptr, indices=indices)
    for variant in ("optimized", "baseline"):
        _both(arrays, variant, 3, max_supersteps=5 * n)


def test_vectorized_is_default_and_faster_path_exists():
    arrays = generate_random_graph(40, 4, seed=0)
    eng = ReferenceSimEngine(arrays)
    assert eng.impl == "vectorized"
    with pytest.raises(ValueError):
        ReferenceSimEngine(arrays, impl="numba")


def test_concat_ranges_rejects_zero_length_rows():
    # ADVICE r5 #4: a real ValueError, not an assert — ``python -O``
    # strips asserts and a zero-length row silently corrupts the offsets
    from dgc_tpu.engine.reference_sim import _concat_ranges

    indptr = np.array([0, 2, 2, 5], np.int64)
    ids = np.array([0, 1, 2], np.int64)
    lens = (indptr[ids + 1] - indptr[ids]).astype(np.int64)
    with pytest.raises(ValueError, match="zero-length"):
        _concat_ranges(indptr, ids, lens)
    # the valid subset still works
    ok = _concat_ranges(indptr, np.array([0, 2], np.int64),
                        np.array([2, 3], np.int64))
    assert ok.tolist() == [0, 1, 2, 3, 4]

"""bench.py contract tests — the driver captures the round's number by
running ``python bench.py`` and parsing ONE JSON line from stdout, so the
line's schema is a hard interface, not an implementation detail."""

import json
import os
import subprocess
import sys


def _run_bench(*args, env_extra=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = repo  # keep the axon sitecustomize off the path
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), *args],
        env=env, cwd=repo, capture_output=True, text=True, timeout=600,
    )


def test_bench_emits_contract_json():
    r = _run_bench("--nodes", "400", "--avg-degree", "6")
    assert r.returncode == 0, r.stderr
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    # the driver contract fields
    assert set(d) >= {"metric", "value", "unit", "vs_baseline"}
    assert d["unit"] == "s" and d["value"] > 0
    # round-4 companions: pass timed beside the sweep, counts unambiguous
    assert d["post_reduce_colors"] <= d["sweep_colors"]
    assert d["post_reduce_s"] >= 0
    # round-5: the user-visible wall-clock (sweep + pass + validation)
    # must be published beside the sweep metric as an exact identity over
    # the rounded fields, so headline and experienced time can't drift
    assert d["validate_s"] >= 0
    expected = round(d["value"] + d["post_reduce_s"] + d["validate_s"], 4)
    assert abs(d["total_s"] - expected) < 1e-9, d


def test_bench_help_is_robust_to_malformed_env():
    r = _run_bench("--help", env_extra={"DGC_TPU_BENCH_PROBE_TIMEOUT": "junk",
                                        "DGC_TPU_BENCH_RUN_TIMEOUT": ""})
    assert r.returncode == 0, r.stderr
    assert "--probe-timeout" in r.stdout

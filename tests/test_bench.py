"""bench.py contract tests — the driver captures the round's number by
running ``python bench.py`` and parsing ONE JSON line from stdout, so the
line's schema is a hard interface, not an implementation detail."""

import json
import os
import subprocess
import sys


def _run_bench(*args, env_extra=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = repo  # keep the axon sitecustomize off the path
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), *args],
        env=env, cwd=repo, capture_output=True, text=True, timeout=600,
    )


def test_bench_emits_contract_json():
    r = _run_bench("--nodes", "400", "--avg-degree", "6")
    assert r.returncode == 0, r.stderr
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    # the driver contract fields
    assert set(d) >= {"metric", "value", "unit", "vs_baseline"}
    assert d["unit"] == "s" and d["value"] > 0
    # round-4 companions: pass timed beside the sweep, counts unambiguous
    assert d["post_reduce_colors"] <= d["sweep_colors"]
    assert d["post_reduce_s"] >= 0
    # round-5: the user-visible wall-clock (sweep + pass + validation)
    # must be published beside the sweep metric as an exact identity over
    # the rounded fields, so headline and experienced time can't drift
    assert d["validate_s"] >= 0
    expected = round(d["value"] + d["post_reduce_s"] + d["validate_s"], 4)
    assert abs(d["total_s"] - expected) < 1e-9, d


def test_bench_abort_record_carries_partial_phases():
    """rc-113 contract: the backend-unreachable null record must carry
    the probed context and the partial per-phase breakdown collected
    before the abort — not only the error metric. Simulated with the
    fault plane's device_init hang under a short probe watchdog."""
    r = _run_bench("--nodes", "400", "--avg-degree", "6",
                   "--inject-faults", "device_init@1=hang:30",
                   "--probe-timeout", "2")
    assert r.returncode == 113, (r.returncode, r.stderr)
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    assert d["metric"] == "bench_aborted_backend_unreachable"
    assert d["value"] is None and d["vs_baseline"] == 0.0
    # the partial breakdown + context, not only the error metric
    assert "phases" in d and isinstance(d["phases"], dict)
    assert d["backend"] == "ell-compact" and d["probed"] is False
    assert "# BENCH ABORTED" in r.stderr


def test_serve_throughput_shares_the_abort_contract():
    """--serve-throughput must reuse the same rc-113 record shape
    (satellite contract: serve metrics abort exactly like sweep
    metrics, partial phases included)."""
    r = _run_bench("--serve-throughput", "--nodes", "300",
                   "--serve-graphs", "1", "--serve-batch-sizes", "1",
                   "--inject-faults", "device_init@1=hang:30",
                   "--probe-timeout", "2")
    assert r.returncode == 113, (r.returncode, r.stderr)
    d = json.loads([l for l in r.stdout.splitlines()
                    if l.startswith("{")][0])
    assert d["metric"] == "serve_aborted_backend_unreachable"
    assert "phases" in d and d["backend"] == "serve"


def test_serve_throughput_contract_json():
    r = _run_bench("--serve-throughput", "--nodes", "400",
                   "--avg-degree", "6", "--serve-graphs", "2",
                   "--serve-batch-sizes", "1,2")
    assert r.returncode == 0, r.stderr
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    d = json.loads(lines[0])
    assert d["unit"] == "graphs/s" and d["value"] > 0
    assert d["parity_ok"] is True
    assert set(d["batches"]) == {"1", "2"}
    assert d["sequential_graphs_per_s"] > 0
    assert "sequential_s" in d["phases"] and "serve_b2_s" in d["phases"]


def test_bench_help_is_robust_to_malformed_env():
    r = _run_bench("--help", env_extra={"DGC_TPU_BENCH_PROBE_TIMEOUT": "junk",
                                        "DGC_TPU_BENCH_RUN_TIMEOUT": ""})
    assert r.returncode == 0, r.stderr
    assert "--probe-timeout" in r.stdout

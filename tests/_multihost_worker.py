"""Worker for the two-process ``jax.distributed`` smoke test.

Launched twice by ``test_multihost.py`` (one subprocess per process id) with
a localhost coordinator and the CPU backend. Executes the explicit-coordinator
branch of ``initialize_multihost`` (``parallel/multihost.py``), then runs a
tiny sharded k-attempt over the 2-process global mesh — the reference's
cluster-config story (``/root/reference/coloring.py:190-199``) exercised for
real rather than parsed.

Usage: python tests/_multihost_worker.py PORT PROCESS_ID OUTDIR [MODE]

MODE ``smoke`` (default): the engine/sweep assertions. MODE ``preempt``:
minimal-k sweep with checkpointing where the FIRST launch of the pair
self-terminates right after the fused pair's first half is checkpointed
(a coordinated pod preemption); a relaunch with the same OUTDIR resumes
from the per-process checkpoints and completes. The reference has no
analog (SURVEY §5: no checkpointing) — this is the failure-recovery story
the TPU build adds, exercised across real process boundaries.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

port, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "smoke"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.parallel.multihost import initialize_multihost, process_info  # noqa: E402

is_multi = initialize_multihost(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)

import jax  # noqa: E402  (backend init happens after distributed init)

assert is_multi, "initialize_multihost returned False for a 2-process setup"
info = process_info()
assert info["process_count"] == 2, info
assert info["global_devices"] == 2 * info["local_devices"], info

from dgc_tpu.engine.base import AttemptStatus  # noqa: E402
from dgc_tpu.engine.sharded import ShardedELLEngine  # noqa: E402
from dgc_tpu.engine.sharded_bucketed import ShardedBucketedEngine  # noqa: E402
from dgc_tpu.models.generators import (  # noqa: E402
    generate_random_graph,
    generate_rmat_graph,
)
from dgc_tpu.parallel.mesh import make_mesh  # noqa: E402

mesh = make_mesh(len(jax.devices()))

if mode == "preempt":
    from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
    from dgc_tpu.utils.checkpoint import CheckpointManager, graph_fingerprint

    gp = generate_rmat_graph(256, avg_degree=6, seed=9, native=False)
    eng = ShardedBucketedEngine(gp, mesh=mesh)
    ckpt = CheckpointManager(
        os.path.join(outdir, f"ck_{pid}"),
        fingerprint=graph_fingerprint(gp, "sharded-bucketed", False),
    )
    first_launch = not os.path.exists(os.path.join(outdir, f"launched_{pid}"))
    open(os.path.join(outdir, f"launched_{pid}"), "w").write("x")
    calls = 0

    def preempt(res, val):
        # on_attempt fires BEFORE checkpoint.save, so dying on the SECOND
        # callback leaves exactly the pair's first half saved — both
        # processes reach this point together (the sweep's device call has
        # already completed on both), so nobody hangs in a collective
        global calls
        calls += 1
        if first_launch and calls == 2:
            os._exit(7)

    result = find_minimal_coloring(
        eng, gp.max_degree + 1, validate=make_validator(gp),
        checkpoint=ckpt, on_attempt=preempt,
    )
    with open(os.path.join(outdir, f"preempt_result_{pid}.json"), "w") as f:
        json.dump({"minimal_colors": result.minimal_colors,
                   "colors": result.colors.tolist(),
                   "attempts": [[a.k, int(a.status)] for a in result.attempts],
                   "info": process_info()}, f)
    print(f"worker {pid} preempt-resume OK")
    sys.exit(0)

g = generate_random_graph(50, 5, seed=7)  # same seed on both processes
engine = ShardedELLEngine(g, mesh=mesh)
res = engine.attempt(g.max_degree + 1)
assert res.status == AttemptStatus.SUCCESS, res.status

# heavy-tail engine over the same 2-process mesh (degree-dealt buckets,
# frontier gating) — the multi-chip power-law path across real processes
gr = generate_rmat_graph(256, avg_degree=6, seed=9, native=False)
engb = ShardedBucketedEngine(gr, mesh=mesh)
resb = engb.attempt(gr.max_degree + 1)
assert resb.status == AttemptStatus.SUCCESS, resb.status

# fused sweep with prefix-resume across the process boundary: the
# ring-push decision is pmax/psum-derived (process-uniform), and the
# confirm must match a scratch attempt exactly — superstep counter
# included (the device_sweep_pair_resumable contract)
s1, s2 = engb.sweep(gr.max_degree + 1)
assert s1.supersteps == resb.supersteps, (s1.supersteps, resb.supersteps)
assert s1.colors.tolist() == resb.colors.tolist()
if resb.colors_used > 1:
    # same-engine baseline: the sweep contract is "bit-identical to two
    # attempt calls on THIS engine" (window-widening state included)
    rc = engb.attempt(resb.colors_used - 1)
    assert s2.status == rc.status and s2.supersteps == rc.supersteps, \
        (s2.status, rc.status, s2.supersteps, rc.supersteps)
    assert s2.colors.tolist() == rc.colors.tolist()

with open(os.path.join(outdir, f"result_{pid}.json"), "w") as f:
    json.dump({"info": info, "colors": res.colors.tolist(),
               "supersteps": res.supersteps,
               "rmat_colors": resb.colors.tolist(),
               "sweep_confirm_k": None if s2 is None else s2.k}, f)
print(f"worker {pid} OK: {info}")

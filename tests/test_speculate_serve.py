"""Speculative minimal-k (serve-tier outer-k-loop parallelism).

Locks the three load-bearing properties of
:class:`dgc_tpu.serve.speculate.SpeculativeMinimalKEngine`:

- **Byte-identity** — a strict-decrement sweep driven through the
  speculative engine yields the exact colors, minimal k, and attempt
  sequence of the sequential single-graph reference, across telemetry
  on/off and mesh on/off (the 12-draw parity ensemble).
- **Cancellation** — losers die at slice boundaries (S=1 makes every
  superstep a boundary, staged ladders make every rung transition one),
  the stopping rule cancels the whole window at first failure, and the
  wasted-superstep account is charged.
- **Starvation-freedom** — speculation seats strictly below real
  traffic: a real wave arriving while speculation holds lanes preempts
  the speculative lanes THIS slice.
"""

from __future__ import annotations

import io
import threading

import numpy as np
import pytest

from dgc_tpu.engine.compact import CompactFrontierEngine
from dgc_tpu.engine.minimal_k import (find_minimal_coloring, make_reducer,
                                      make_validator)
from dgc_tpu.models.generators import (generate_random_graph_fast,
                                       generate_rmat_graph)
from dgc_tpu.serve.engine import BatchMemberEngine, BatchScheduler
from dgc_tpu.serve.queue import ServeFrontEnd
from dgc_tpu.serve.shape_classes import DEFAULT_LADDER, pad_member
from dgc_tpu.serve.speculate import (AUTO_DEPTH_CAP,
                                     SpeculativeMinimalKEngine, auto_depth)


def _strict_reference(g):
    """The parity target: the sequential single-graph strict-decrement
    sweep with the CLI defaults (validate + recolor pass)."""
    attempts = []
    res = find_minimal_coloring(
        CompactFrontierEngine(g), initial_k=g.max_degree + 1,
        strict_decrement=True, validate=make_validator(g),
        on_attempt=lambda r, v: attempts.append(
            (int(r.k), r.status.name, int(r.supersteps))),
        post_reduce=make_reducer(g))
    return res, attempts


def _speculative_run(g, sched, depth=2, on_attempt_list=None):
    cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
    engine = SpeculativeMinimalKEngine(pad_member(g, cls), sched,
                                       depth=depth)
    attempts = [] if on_attempt_list is None else on_attempt_list
    try:
        res = find_minimal_coloring(
            engine, initial_k=engine.member.k0, strict_decrement=True,
            validate=make_validator(g),
            on_attempt=lambda r, v: attempts.append(
                (int(r.k), r.status.name, int(r.supersteps))),
            post_reduce=make_reducer(g))
    finally:
        engine.close()
    return res, attempts, engine


# -- auto depth ---------------------------------------------------------

def test_auto_depth_policy():
    # free lanes bound the window; cap bounds deep pools; floor is 1
    assert auto_depth(2) == 1
    assert auto_depth(4) == 3
    assert auto_depth(8) == AUTO_DEPTH_CAP
    assert auto_depth(8, live=6) == 1
    assert auto_depth(1) == 1
    assert auto_depth(16, cap=8) == 8


def test_depth_must_be_positive():
    sched = BatchScheduler(batch_max=2).start()
    try:
        g = generate_random_graph_fast(60, avg_degree=4, seed=0)
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        with pytest.raises(ValueError):
            SpeculativeMinimalKEngine(pad_member(g, cls), sched, depth=0)
    finally:
        sched.stop()


# -- byte-identity parity ensemble --------------------------------------

def test_speculative_strict_parity_ensemble():
    """12 draws x {telemetry on/off} x {mesh on/off}: the speculative
    strict-decrement sweep is byte-identical to the sequential
    single-graph reference — colors, minimal k, and the full attempt
    sequence (k, status, supersteps per attempt)."""
    draws = []
    for i in range(12):
        gen = (generate_rmat_graph if i % 3 == 2
               else generate_random_graph_fast)
        draws.append(gen(240 + 20 * i, avg_degree=4 + i % 2,
                         seed=100 + i))
    configs = [(telemetry, mesh) for telemetry in (False, True)
               for mesh in (False, True)]
    for ci, (telemetry, mesh) in enumerate(configs):
        events = []
        kw = dict(batch_max=4, window_s=0.0, slice_steps=4)
        if telemetry:
            kw["on_event"] = lambda kind, rec: events.append((kind, rec))
        if mesh:
            kw["mesh_devices"] = "auto"
        sched = BatchScheduler(**kw).start()
        try:
            for g in draws[ci * 3:(ci + 1) * 3]:
                want, want_attempts = _strict_reference(g)
                got, got_attempts, eng = _speculative_run(g, sched,
                                                          depth=2)
                assert got.minimal_colors == want.minimal_colors
                assert np.array_equal(got.colors, want.colors)
                assert got_attempts == want_attempts
                # the window actually speculated (overlap existed)
                assert eng.spec_stats["speculated"] > 0
                assert eng.spec_stats["claims"] > 0
            stats = sched.stats_snapshot()
            assert stats["spec_seated"] > 0
            assert stats["spec_wins"] > 0
        finally:
            sched.stop()
        if telemetry:
            kinds = {k for k, _ in events}
            assert "spec_seated" in kinds
            assert "spec_win" in kinds


def test_jump_mode_is_inert():
    """Without --strict-decrement the driver runs the fused find/confirm
    pair through ``sweep`` — the speculative proxy must delegate and
    never seat a single speculative attempt, so the default serve path
    stays byte-identical (events included) with speculation armed."""
    g = generate_random_graph_fast(500, avg_degree=6, seed=11)
    events = []
    sched = BatchScheduler(batch_max=4, window_s=0.0,
                           on_event=lambda k, r: events.append(k)).start()
    try:
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        engine = SpeculativeMinimalKEngine(pad_member(g, cls), sched,
                                           depth=3)
        try:
            got = find_minimal_coloring(
                engine, initial_k=engine.member.k0,
                validate=make_validator(g), post_reduce=make_reducer(g))
        finally:
            engine.close()
        ref = find_minimal_coloring(
            CompactFrontierEngine(g), initial_k=g.max_degree + 1,
            validate=make_validator(g), post_reduce=make_reducer(g))
        assert got.minimal_colors == ref.minimal_colors
        assert np.array_equal(got.colors, ref.colors)
        assert engine.spec_stats["speculated"] == 0
        stats = sched.stats_snapshot()
        assert stats["spec_seated"] == 0
        assert not any(k.startswith("spec_") for k in events)
    finally:
        sched.stop()


# -- cancellation at slice boundaries -----------------------------------

_STAGE_LADDERS = (
    "off",                              # full-table kernel, 1 rung
    ((None, 128), (128, 0)),            # 2-rung ladder
    ((None, 512), (512, 128), (128, 0)),  # 3-rung ladder
)


@pytest.mark.parametrize("stages", _STAGE_LADDERS,
                         ids=["off", "rungs2", "rungs3"])
def test_slice_boundary_cancellation_every_stage_rung(stages):
    """S=1 makes EVERY superstep a slice boundary — including every
    stage-rung transition of the staged frontier ladder — and the
    stopping rule's first failure cancels the live window there. The
    killed lanes charge their burned supersteps, and parity holds."""
    g = generate_random_graph_fast(450, avg_degree=7, seed=77)
    events = []
    sched = BatchScheduler(
        batch_max=4, window_s=0.0, slice_steps=1, stages=stages,
        on_event=lambda kind, rec: events.append((kind, rec))).start()
    try:
        want, want_attempts = _strict_reference(g)
        got, got_attempts, eng = _speculative_run(g, sched, depth=3)
        assert got.minimal_colors == want.minimal_colors
        assert np.array_equal(got.colors, want.colors)
        assert got_attempts == want_attempts
        stats = sched.stats_snapshot()
        # the failing attempt ends the sweep with budgets below it still
        # speculating: they MUST be cancelled, not claimed
        assert stats["spec_cancelled"] > 0
    finally:
        sched.stop()
    cancelled = [rec for kind, rec in events if kind == "spec_cancelled"]
    assert cancelled
    assert all(rec["where"] in ("queue", "lane", "done")
               for rec in cancelled)
    # every cancel is below the sweep's answer+... the failure budget:
    # the window never held a budget the sequential schedule consumed
    fail_k = min(k for k, _, _ in want_attempts)
    assert all(rec["k"] <= fail_k for rec in cancelled)
    # seated-lane kills report the supersteps they burned
    lane_kills = [rec for rec in cancelled if rec["where"] == "lane"]
    for rec in lane_kills:
        assert rec.get("wasted_steps", 0) >= 0


def test_close_cancels_outstanding_window():
    """An abandoned sweep (engine.close without reaching the window)
    frees every speculative lane instead of leaking it."""
    g = generate_random_graph_fast(400, avg_degree=6, seed=31)
    sched = BatchScheduler(batch_max=4, window_s=0.0,
                           slice_steps=1).start()
    try:
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        engine = SpeculativeMinimalKEngine(pad_member(g, cls), sched,
                                           depth=3)
        engine.attempt(engine.member.k0)   # seeds the window below k0
        assert engine._window
        engine.close()
        assert not engine._window
        stats = sched.stats_snapshot()
        assert stats["spec_cancelled"] >= 1
    finally:
        sched.stop()


# -- starvation-freedom: real traffic preempts speculation --------------

def test_real_requests_preempt_speculative_lanes():
    """Speculation seats strictly below queued traffic: with every lane
    speculative and a real wave larger than the free capacity, the
    dispatcher preempts the speculative lanes the same slice and seats
    the real wave — speculation can never starve a paying request."""
    slow = generate_random_graph_fast(900, avg_degree=12, seed=50)
    cls = DEFAULT_LADDER.class_for(slow.num_vertices, slow.max_degree)
    sched = BatchScheduler(batch_max=2, window_s=0.0,
                           slice_steps=1).start()
    try:
        member = pad_member(slow, cls)
        # fill both lanes with speculative attempts (deep budgets: long
        # frontier chains keep the lanes busy)
        calls = [sched.speculate(member, member.k0 - 1 - i)
                 for i in range(2)]
        assert all(c is not None for c in calls)
        import time
        deadline = time.time() + 30
        while (sched.stats_snapshot()["spec_seated"] < 1
               and time.time() < deadline):
            time.sleep(0.005)
        assert sched.stats_snapshot()["spec_seated"] >= 1

        # a real wave bigger than the free capacity arrives
        real = [generate_random_graph_fast(300 + 40 * i, avg_degree=5,
                                           seed=60 + i) for i in range(3)]
        results = {}

        def run_real(i, g):
            eng = BatchMemberEngine(pad_member(g, cls), sched)
            results[i] = find_minimal_coloring(
                eng, initial_k=eng.member.k0, validate=make_validator(g),
                post_reduce=make_reducer(g))

        threads = [threading.Thread(target=run_real, args=(i, g))
                   for i, g in enumerate(real)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert len(results) == 3
        for i, g in enumerate(real):
            ref = find_minimal_coloring(
                CompactFrontierEngine(g), initial_k=g.max_degree + 1,
                validate=make_validator(g), post_reduce=make_reducer(g))
            assert results[i].minimal_colors == ref.minimal_colors
            assert np.array_equal(results[i].colors, ref.colors)
        stats = sched.stats_snapshot()
        assert stats["spec_preempted"] >= 1
        preempted = [c for c in calls
                     if c.cancelled and c.cancel_reason == "preempted"]
        assert preempted
        for c in calls:
            sched.cancel_speculative(c, "test done")
    finally:
        sched.stop()


# -- serve front end wiring ---------------------------------------------

def test_frontend_speculate_k_auto_resolves_and_serves():
    """``speculate_k='auto'`` resolves against batch_max, and serve
    requests (jump mode) stay byte-identical with speculation armed —
    the engine substitution is inert by construction there."""
    from dgc_tpu.obs import RunLogger

    g = generate_random_graph_fast(400, avg_degree=6, seed=90)
    stream = io.StringIO()
    fe = ServeFrontEnd(batch_max=4, window_s=0.0, queue_depth=8,
                       speculate_k="auto",
                       logger=RunLogger(stream=stream, echo=False)).start()
    try:
        assert fe.speculate_k == auto_depth(4)
        r = fe.submit(g).result(timeout=600)
        assert r.ok
        ref = find_minimal_coloring(
            CompactFrontierEngine(g), initial_k=g.max_degree + 1,
            validate=make_validator(g), post_reduce=make_reducer(g))
        assert r.minimal_colors == ref.minimal_colors
        assert np.array_equal(r.colors, ref.colors)
    finally:
        fe.shutdown()
    # jump-mode serve requests never seat speculation
    assert '"spec_seated"' not in stream.getvalue()


def test_frontend_speculate_k_validation():
    with pytest.raises(ValueError):
        ServeFrontEnd(batch_max=2, speculate_k=0)

#!/usr/bin/env python
"""Chaos harness for the crash-safe serve tier: seeded serve-point fault
schedules + SIGKILL/resume cycles over the durable ticket journal.

The serve-tier analogue of ``tools/chaos_sweep.py``. Two legs, one
report:

**Leg 1 — seeded serve-point schedules (in-process).** ``--schedules N``
runs of a full serving stack (``ServeFrontEnd`` + admission + the
``NetFront`` listener + ticket journal), each under a deterministic
:meth:`FaultSchedule.random_serve` draw; a round-robin ``must_cover``
guarantees every serve injection point (``serve_dispatch``,
``lane_seat``, ``deliver``, ``journal_write``, ``net_accept``) is
exercised. The invariant per schedule:

    every accepted (202) ticket reaches a terminal result — either
    ``ok`` with colors **bit-identical to the fault-free run** of the
    same request, or a STRUCTURED failure carrying rc context (the
    quarantine / delivery-abort / journal-error paths) — within the
    harness deadline. Never a hang, never a silently wrong coloring,
    never a lost or duplicated ticket. The run log schema-validates.

**Leg 2 — kill-resume soak (real processes).** The serve CLI
(``dgc-tpu serve --listen --journal-dir``) runs as a subprocess; N
concurrent clients submit generator-spec requests and poll through
restarts. A watcher thread SIGKILLs the server whenever the journal
crosses the next of ``--kills`` seeded record offsets (drawn against
the fault-free run's journal length); the harness restarts it — same
command, same ``--journal-dir`` — the way a rolling-restart supervisor
would. Asserted at the end:

    zero acked-ticket loss (every 202 polls to a terminal 200 after the
    last restart), zero duplicate ticket ids across ALL incarnations
    (the high-water-mark seeding), no duplicate deliveries (a ticket's
    result is stable across repeated polls), and every replayed
    request's colors byte-identical to the fault-free baseline.

Fleet-telemetry invariants ride leg 2: clients propagate deterministic
per-seed W3C ``traceparent`` headers, and the post-soak asserts prove
(a) per-tenant usage conservation — the journal fold
(``tools/usage_export.py``) EXACTLY equals the raw journal totals
across all incarnations and the ``usage_rollup`` artifact
schema-validates — and (b) cross-incarnation trace continuity — every
journal-replayed ticket's trace id carries spans in ≥2 incarnations'
logs and the merged Perfetto export (``tools/export_trace.py``) shows
one track with multiple incarnation lanes.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_serve.py --schedules 5 --kills 3 \\
        --clients 8 --requests-per-client 2 --nodes 500 --degree 6 \\
        --report /tmp/chaos_serve.json
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dgc_tpu.resilience.faults import SERVE_POINTS, FaultSchedule  # noqa: E402
from tools.validate_runlog import validate_file  # noqa: E402

CHAOS_SERVE_REPORT_VERSION = 1

_OUTCOMES = ("ok", "structured", "hang", "error", "mismatch")


# ---------------------------------------------------------------------------
# shared HTTP plumbing (retries across restarts)
# ---------------------------------------------------------------------------

def _http(method: str, port: int, path: str, doc=None, tenant=None,
          retries: int = 120, deadline_s: float = 240.0,
          headers_extra=None):
    """One request, retried through connection failures (the server may
    be dead between a SIGKILL and its restart) with capped backoff.
    Returns (status, body_doc)."""
    body = json.dumps(doc).encode() if doc is not None else None
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Dgc-Tenant"] = tenant
    if headers_extra:
        headers.update(headers_extra)
    t_end = time.perf_counter() + deadline_s
    last = None
    for attempt in range(retries):
        if time.perf_counter() > t_end:
            break
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, (json.loads(payload) if payload else {})
        except (OSError, http.client.HTTPException,
                json.JSONDecodeError) as e:
            last = e
            time.sleep(min(1.0, 0.05 * (attempt + 1)))
        finally:
            try:
                conn.close()
            except OSError:
                pass
    raise RuntimeError(f"server unreachable on :{port}: {last}")


def _request_doc(nodes: int, degree: int, seed: int) -> dict:
    return {"node_count": nodes, "max_degree": degree, "seed": seed,
            "gen_method": "fast"}


def _traceparent_ids(seed: int) -> tuple[str, str]:
    """Deterministic W3C (trace_id, parent_id) for one request seed —
    the kill-resume clients propagate these so a replayed ticket's
    resumed spans are provably the CALLER's trace, not a fresh one."""
    h = hashlib.sha256(f"chaos-serve-{seed}".encode()).hexdigest()
    return h[:32], h[32:48]


# ---------------------------------------------------------------------------
# leg 1: in-process seeded serve-point schedules
# ---------------------------------------------------------------------------

def _stand_stack(workdir: str, args, logger):
    """One in-process serving stack over a fresh journal dir."""
    from dgc_tpu.serve.netfront import NetFront
    from dgc_tpu.serve.queue import ServeFrontEnd

    mesh_devices = getattr(args, "mesh_devices", None)
    if mesh_devices not in (None, "auto"):
        mesh_devices = int(mesh_devices)
    front = ServeFrontEnd(
        batch_max=args.batch_max, window_s=0.0,
        queue_depth=max(64, args.clients * args.requests_per_client * 2),
        dispatch_timeout=args.dispatch_timeout,
        max_lane_aborts=args.max_lane_aborts,
        mesh_devices=mesh_devices,
        logger=logger).start()
    nf = NetFront(front, logger=logger,
                  journal_dir=os.path.join(workdir, "journal")).start()
    return front, nf


def _drive_requests(port: int, reqs: list, deadline_s: float):
    """Submit every request doc then poll each accepted ticket to a
    terminal result. Returns (tickets, results, rejects, errors):
    ``results[ticket]`` is the final 200 body (colors included)."""
    tickets: list = []
    rejects = 0
    errors: list = []
    for doc in reqs:
        accepted = False
        for _ in range(60):
            st, body = _http("POST", port, "/v1/color", doc,
                             deadline_s=deadline_s)
            if st == 202:
                tickets.append(body["ticket"])
                accepted = True
                break
            if st in (429, 503):
                rejects += 1
                time.sleep(0.05)
                continue
            errors.append(f"submit HTTP {st}: {body}")
            break
        if not accepted and not errors:
            errors.append("submit never accepted")
    results: dict = {}
    t_end = time.perf_counter() + deadline_s
    for ticket in tickets:
        while True:
            if time.perf_counter() > t_end:
                errors.append(f"poll deadline for {ticket}")
                break
            st, body = _http("GET", port, f"/v1/result/{ticket}?colors=1",
                             deadline_s=deadline_s)
            if st == 200:
                results[ticket] = body
                break
            if st == 202:
                time.sleep(0.02)
                continue
            errors.append(f"poll {ticket} HTTP {st}")
            break
    return tickets, results, rejects, errors


def _baseline_colors(args, reqs: list) -> dict:
    """Fault-free in-process run: request seed -> colors (the
    bit-identity reference for both legs)."""
    from dgc_tpu.obs import RunLogger

    workdir = tempfile.mkdtemp(prefix="dgc_chaos_serve_base_")
    logger = RunLogger(jsonl_path=None, echo=False)
    front, nf = _stand_stack(workdir, args, logger)
    try:
        _tickets, results, _rej, errors = _drive_requests(
            nf.port, reqs, args.deadline)
        if errors:
            raise RuntimeError(f"fault-free baseline failed: {errors[:3]}")
        by_seed = {}
        for doc in results.values():
            if doc.get("status") != "ok":
                raise RuntimeError(f"fault-free baseline non-ok: {doc}")
        # map ticket order back to request order (tickets are issued in
        # submit order and _drive_requests submits sequentially)
        for req, ticket in zip(reqs, _tickets):
            by_seed[req["seed"]] = results[ticket]["colors"]
        return by_seed
    finally:
        nf.close()
        front.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


_STRUCTURED_MARKERS = ("rc 114", "quarantined", "delivery aborted",
                       "journal replay failed")


def _run_schedule(index: int, args, reqs: list, baseline: dict) -> dict:
    """One seeded schedule against a fresh stack; returns the report
    entry."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.resilience import faults

    rng = random.Random(args.seed * 61_001 + index)
    must = SERVE_POINTS[index % len(SERVE_POINTS)]
    schedule = FaultSchedule.random_serve(
        rng, n_faults=rng.randint(1, args.max_faults), must_cover=must,
        hang_seconds=min(2.0, args.dispatch_timeout + 0.5))
    spec = schedule.to_spec()
    entry = {"index": index, "spec": spec, "must_cover": must,
             "fired": 0, "log_problems": 0, "outcome": "error"}
    workdir = tempfile.mkdtemp(prefix="dgc_chaos_serve_")
    log = os.path.join(workdir, "run.jsonl")
    logger = RunLogger(jsonl_path=log, echo=False)
    plane = faults.FaultPlane(schedule)
    front = nf = None
    try:
        with faults.injected(plane):
            front, nf = _stand_stack(workdir, args, logger)
            tickets, results, rejects, errors = _drive_requests(
                nf.port, reqs, args.deadline)
        entry["fired"] = len(plane.fired_snapshot())
        entry["rejects"] = rejects
        if len(set(tickets)) != len(tickets):
            errors.append("duplicate ticket ids")
        structured = 0
        mismatched = 0
        for req, ticket in zip(reqs, tickets):
            doc = results.get(ticket)
            if doc is None:
                continue   # already accounted as a poll error
            if doc.get("status") == "ok":
                if doc.get("colors") != baseline[req["seed"]]:
                    mismatched += 1
            elif any(m in (doc.get("error") or "")
                     for m in _STRUCTURED_MARKERS):
                structured += 1
            else:
                errors.append(f"unstructured failure: {doc.get('error')}")
        entry["structured"] = structured
        if os.path.exists(log):
            entry["log_problems"] = len(validate_file(log))
        if mismatched:
            entry["outcome"] = "mismatch"
        elif errors or entry["log_problems"] or len(results) != len(tickets):
            entry["outcome"] = "error"
            entry["errors"] = errors[:5]
        else:
            entry["outcome"] = "structured" if structured else "ok"
    except RuntimeError as e:
        entry["outcome"] = "hang" if "unreachable" in str(e) else "error"
        entry["errors"] = [str(e)[:300]]
    finally:
        if nf is not None:
            nf.close()
        if front is not None:
            front.shutdown()
        logger.close()
        shutil.rmtree(workdir, ignore_errors=True)
    return entry


# ---------------------------------------------------------------------------
# leg 2: SIGKILL at seeded journal offsets, restart, resume
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Server:
    """The serve CLI as a managed subprocess (one incarnation)."""

    def __init__(self, port: int, journal_dir: str, log_path: str, args):
        self.args = [sys.executable, "-m", "dgc_tpu.cli", "serve",
                     "--listen", str(port), "--journal-dir", journal_dir,
                     "--log-json", log_path,
                     "--batch-max", str(args.batch_max),
                     "--queue-depth",
                     str(max(64, args.clients
                             * args.requests_per_client * 2)),
                     "--window-ms", "0",
                     "--dispatch-timeout", str(args.dispatch_timeout),
                     "--max-lane-aborts", str(args.max_lane_aborts)]
        # harness-composition hook (tools/chaos_mesh.py): extra serve-CLI
        # flags every incarnation runs with — e.g. --mesh-devices 8 plus
        # an injected device_loss, so the kill-resume soak exercises a
        # DEGRADED mesh's journal recovery
        self.args += list(getattr(args, "server_extra", []) or [])
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            self.args, env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.port = port

    def wait_ready(self, deadline_s: float = 120.0) -> None:
        t_end = time.perf_counter() + deadline_s
        while time.perf_counter() < t_end:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"server exited rc {self.proc.returncode} "
                    f"before ready")
            try:
                st, _doc = _http("GET", self.port, "/healthz", retries=1,
                                 deadline_s=5.0)
                if st == 200:
                    return
            except RuntimeError:
                pass
            time.sleep(0.1)
        raise RuntimeError("server never became ready")

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)


def _journal_records(path: str) -> int:
    try:
        with open(path, "rb") as fh:
            return fh.read().count(b"\n")
    except OSError:
        return 0


def _run_kill_resume(args, reqs: list, baseline: dict) -> dict:
    """The kill-resume soak: drive clients, SIGKILL at seeded journal
    offsets, restart over the same journal, assert nothing acked was
    lost and every color matches the fault-free run."""
    from dgc_tpu.serve.netfront.journal import JOURNAL_FILE

    workdir = args.workdir or tempfile.mkdtemp(prefix="dgc_chaos_kill_")
    os.makedirs(workdir, exist_ok=True)
    journal_dir = os.path.join(workdir, "journal")
    journal_path = os.path.join(journal_dir, JOURNAL_FILE)
    port = _free_port()
    entry = {"kills_planned": int(args.kills), "kills": 0, "restarts": 0,
             "incarnations": 1, "outcome": "error", "log_problems": 0}
    errors: list = []

    # seed the kill offsets against the expected WAL record count: 2
    # records per request (admitted + seated; results ride a separate
    # file) — the exact rhythm doesn't matter, only that the offsets
    # land mid-soak and are the same for every run of the same --seed
    expect = max(6, 2 * len(reqs))
    rng = random.Random(args.seed * 93_077 + 17)
    hi = max(4, expect - 2)
    offsets = sorted(rng.sample(range(2, hi),
                                min(args.kills, hi - 2)))
    entry["offsets"] = offsets

    logs = [os.path.join(workdir, "server_0.jsonl")]
    server = _Server(port, journal_dir, logs[0], args)
    stop_watch = threading.Event()
    kills_done = []

    def watcher():
        """SIGKILL the current incarnation as the journal crosses each
        seeded record offset."""
        pending = list(offsets)
        while pending and not stop_watch.is_set():
            n = _journal_records(journal_path)
            if n >= pending[0]:
                pending.pop(0)
                try:
                    server_box["server"].sigkill()
                except Exception as e:   # noqa: BLE001 — accounting
                    errors.append(f"kill failed: {e}")
                    return
                kills_done.append(n)
            time.sleep(0.005)

    # the restart supervisor: whatever kills the server (the watcher's
    # SIGKILLs), bring it back over the SAME journal dir — the
    # rolling-restart operator loop, automated
    server_box = {"server": server}
    stop_sup = threading.Event()

    def supervisor():
        while not stop_sup.is_set():
            srv = server_box["server"]
            if srv.proc.poll() is not None:
                entry["restarts"] += 1
                logs.append(os.path.join(
                    workdir, f"server_{entry['restarts']}.jsonl"))
                nxt = _Server(port, journal_dir, logs[-1], args)
                try:
                    nxt.wait_ready()
                except RuntimeError as e:
                    errors.append(f"restart failed: {e}")
                    stop_sup.set()
                server_box["server"] = nxt
            time.sleep(0.02)

    # concurrent clients: each submits its requests then polls its own
    # tickets to terminal results, riding _http's reconnect loop through
    # every kill window
    tickets: list = []
    ticket_of: dict = {}
    results: dict = {}
    acct = threading.Lock()

    def client(reqs_slice):
        mine = []
        for doc in reqs_slice:
            # W3C context propagation: every submit carries the caller's
            # deterministic traceparent; the 202 must echo the trace id
            tid, span_id = _traceparent_ids(doc["seed"])
            tp = {"traceparent": f"00-{tid}-{span_id}-01"}
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                try:
                    st, body = _http("POST", port, "/v1/color", doc,
                                     retries=8, deadline_s=30.0,
                                     headers_extra=tp)
                except RuntimeError:
                    continue   # server down: supervisor is on it
                if st == 202:
                    if body.get("trace") != tid:
                        with acct:
                            errors.append(
                                f"202 trace {body.get('trace')!r} != "
                                f"caller trace {tid!r}")
                    with acct:
                        tickets.append(body["ticket"])
                        ticket_of[body["ticket"]] = doc
                    mine.append(body["ticket"])
                    break
                if st in (429, 503):
                    time.sleep(0.05)
                    continue
                with acct:
                    errors.append(f"submit HTTP {st}: {body}")
                break
        for ticket in mine:
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                try:
                    st, body = _http(
                        "GET", port, f"/v1/result/{ticket}?colors=1",
                        retries=8, deadline_s=30.0)
                except RuntimeError:
                    continue
                if st == 200:
                    with acct:
                        results[ticket] = body
                    break
                if st == 202:
                    time.sleep(0.02)
                    continue
                with acct:
                    if st == 404:
                        errors.append(f"acked ticket {ticket} LOST (404)")
                        results[ticket] = {"status": "lost"}
                    else:
                        errors.append(f"poll {ticket} HTTP {st}")
                        results[ticket] = {"status": f"http {st}"}
                break
            else:
                with acct:
                    errors.append(f"poll deadline for {ticket}")

    try:
        server.wait_ready()
        watch = threading.Thread(target=watcher, daemon=True)
        watch.start()
        sup = threading.Thread(target=supervisor, daemon=True)
        sup.start()
        per = max(1, args.requests_per_client)
        slices = [reqs[i:i + per] for i in range(0, len(reqs), per)]
        threads = [threading.Thread(target=client, args=(s,), daemon=True)
                   for s in slices]
        for t in threads:
            t.start()
        deadline = time.perf_counter() + args.deadline
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.perf_counter()))
            if t.is_alive():
                errors.append("client thread past deadline (hang)")
        stop_watch.set()
        stop_sup.set()
        sup.join(timeout=10)
        server = server_box["server"]
        if server.proc.poll() is not None:
            # the last kill landed after the supervisor stopped: one
            # final operator restart so the end-state asserts can run
            entry["restarts"] += 1
            logs.append(os.path.join(
                workdir, f"server_{entry['restarts']}.jsonl"))
            server = _Server(port, journal_dir, logs[-1], args)
            server.wait_ready()
        entry["kills"] = len(kills_done)
        entry["incarnations"] = entry["restarts"] + 1

        # -- the invariants ---------------------------------------------
        if len(set(tickets)) != len(tickets):
            errors.append("duplicate ticket ids across incarnations")
        mismatched = 0
        for ticket, doc in results.items():
            if doc.get("status") != "ok":
                errors.append(f"{ticket}: non-ok terminal {doc.get('status')}"
                              f" ({doc.get('error')})")
            elif doc.get("colors") != baseline[ticket_of[ticket]["seed"]]:
                mismatched += 1
        # duplicate-delivery check: re-polling a delivered ticket (on
        # the final incarnation — possibly across a replay) must
        # converge to the SAME colors, never a second different result
        for ticket in tickets[: min(4, len(tickets))]:
            if results.get(ticket, {}).get("status") != "ok":
                continue
            t_end = time.perf_counter() + 60.0
            while time.perf_counter() < t_end:
                st, again = _http("GET", port,
                                  f"/v1/result/{ticket}?colors=1",
                                  retries=8, deadline_s=30.0)
                if st == 202:   # replayed after the final restart
                    time.sleep(0.05)
                    continue
                if st != 200 or again.get("colors") != results[ticket].get(
                        "colors"):
                    errors.append(f"{ticket}: unstable result across "
                                  f"polls (HTTP {st})")
                break
        # graceful exit: drain, then the CLI loop ends on its own
        try:
            _http("POST", port, "/admin/drain", {}, retries=8,
                  deadline_s=60.0)
            server.proc.wait(timeout=60)
        except (RuntimeError, subprocess.TimeoutExpired):
            server.proc.kill()
        # every incarnation's log must schema-validate (spans torn by
        # the SIGKILL are tolerated per the flight-recorder convention:
        # only the LAST line may be torn; unclosed spans in killed
        # incarnations are expected, so spans are checked only on logs
        # whose process exited cleanly — the final one)
        if os.path.exists(logs[-1]):
            entry["log_problems"] = len(validate_file(logs[-1]))
        try:
            _telemetry_invariants(entry, errors, workdir, journal_path,
                                  logs)
        except Exception as e:   # noqa: BLE001 — a broken telemetry
            # invariant is a chaos FAILURE, not a harness crash
            errors.append(f"telemetry invariants raised: "
                          f"{type(e).__name__}: {e}")
        if mismatched:
            entry["outcome"] = "mismatch"
        elif errors or entry["log_problems"]:
            entry["outcome"] = "error"
            entry["errors"] = errors[:8]
        else:
            entry["outcome"] = "ok"
        return entry
    except RuntimeError as e:
        entry["outcome"] = "hang" if "unreachable" in str(e) \
            or "never became ready" in str(e) else "error"
        entry["errors"] = [str(e)[:300]]
        return entry
    finally:
        stop_watch.set()
        stop_sup.set()
        srv = server_box["server"]
        if srv.proc.poll() is None:
            srv.proc.kill()
        if not args.keep_workdir and args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)


def _jsonl_events(path: str) -> list:
    """All parseable records in one JSONL log (torn tail tolerated —
    SIGKILL can cut the final line mid-write)."""
    out: list = []
    try:
        with open(path) as fh:
            raw = fh.read()
    except OSError:
        return out
    lines = raw.split("\n")
    torn_tail = not raw.endswith("\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if torn_tail and i == len(lines) - 1:
                continue
            raise
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _telemetry_invariants(entry: dict, errors: list, workdir: str,
                          journal_path: str, logs: list) -> None:
    """Post-soak fleet-telemetry assertions over the FINAL journal and
    every incarnation's run log:

    - **usage conservation** — the per-tenant journal fold
      (``obs.usage.fold_journal``) must EXACTLY equal the journal's raw
      totals across all incarnations, and the exported ``usage_rollup``
      artifact must schema-validate;
    - **cross-incarnation trace continuity** — every journal-replayed
      ticket's trace id must carry span events in at least two
      incarnations' logs, and the merged Perfetto export must show one
      process track with multiple incarnation lanes."""
    from dgc_tpu.obs.usage import conservation_problems, fold_journal
    from dgc_tpu.serve.netfront.journal import scan_journal
    from tools.export_trace import merge_chrome_traces, read_spans
    from tools.usage_export import write_artifact

    present = [p for p in logs if os.path.exists(p)]

    # -- usage conservation across incarnations -------------------------
    rows = fold_journal(journal_path, log_paths=present)
    cons = conservation_problems(rows, journal_path)
    entry["usage_tenants"] = len(rows)
    entry["usage_conservation"] = "ok" if not cons else "fail"
    errors.extend(f"usage conservation: {c}" for c in cons[:4])
    artifact = os.path.join(workdir, "usage.jsonl")
    write_artifact(rows, artifact)
    entry["usage_artifact_problems"] = len(validate_file(artifact))
    if entry["usage_artifact_problems"]:
        errors.append("usage_rollup artifact fails schema validation")

    # -- cross-incarnation trace continuity ------------------------------
    labeled = [(os.path.basename(p), read_spans(p)) for p in present]
    files_of_trace: dict = {}    # trace id -> {file index}
    for idx, (_label, spans) in enumerate(labeled):
        for rec in spans:
            files_of_trace.setdefault(rec.get("trace"), set()).add(idx)
    trace_of_ticket = {
        ent.ticket: (ent.trace or f"req-{ent.ticket}")
        for ent in scan_journal(journal_path).tickets}
    replayed = set()
    for path in present[1:]:     # recovery only runs on restart
        for rec in _jsonl_events(path):
            if (rec.get("event") == "net_recover"
                    and rec.get("action") == "replayed"):
                replayed.add(rec.get("ticket"))
    cross = sum(1 for t in replayed
                if len(files_of_trace.get(trace_of_ticket.get(t), ()))
                >= 2)
    entry["replayed_tickets"] = len(replayed)
    entry["cross_incarnation_traces"] = cross
    if replayed and cross == 0:
        errors.append("no replayed ticket's trace id has spans in "
                      "multiple incarnations (trace resume broken)")
    merged = merge_chrome_traces(labeled)
    merged_path = os.path.join(workdir, "trace_merged.json")
    with open(merged_path, "w") as fh:
        json.dump(merged, fh)
        fh.write("\n")
    if cross:
        lanes: dict = {}         # pid -> {tid} over complete events
        for ev in merged["traceEvents"]:
            if ev.get("ph") == "X":
                lanes.setdefault(ev["pid"], set()).add(ev["tid"])
        if not any(len(tids) >= 2 for tids in lanes.values()):
            errors.append("merged Perfetto export has no track spanning "
                          "two incarnation lanes")


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def validate_chaos_serve_report(doc) -> list[str]:
    """Structural check (the chaos_sweep convention: list of problems,
    empty = well-formed)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["report is not an object"]
    if doc.get("chaos_serve_report_version") != CHAOS_SERVE_REPORT_VERSION:
        problems.append("missing/wrong chaos_serve_report_version")
    if not isinstance(doc.get("config"), dict):
        problems.append("missing config object")
    schedules = doc.get("schedules")
    if not isinstance(schedules, list):
        problems.append("missing schedules list")
        schedules = []
    for i, s in enumerate(schedules):
        for fieldname, ty in (("index", int), ("spec", str),
                              ("outcome", str), ("must_cover", str)):
            if not isinstance(s.get(fieldname), ty):
                problems.append(
                    f"schedules[{i}]: missing/invalid {fieldname!r}")
        if s.get("outcome") not in _OUTCOMES:
            problems.append(
                f"schedules[{i}]: unknown outcome {s.get('outcome')!r}")
    kr = doc.get("kill_resume")
    if kr is not None:
        for fieldname in ("kills_planned", "kills", "restarts"):
            if not isinstance(kr.get(fieldname), int):
                problems.append(f"kill_resume: missing/invalid "
                                f"{fieldname!r}")
        if kr.get("outcome") not in _OUTCOMES:
            problems.append(
                f"kill_resume: unknown outcome {kr.get('outcome')!r}")
    summary = doc.get("summary")
    if not isinstance(summary, dict):
        problems.append("missing summary object")
    else:
        for fieldname in ("total", "ok", "structured", "failed"):
            if not isinstance(summary.get(fieldname), int):
                problems.append(f"summary: missing/invalid {fieldname!r}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--schedules", type=int, default=10,
                   help="seeded in-process serve-point schedules (a "
                        "round-robin must_cover guarantees every point)")
    p.add_argument("--kills", type=int, default=3,
                   help="SIGKILL/restart cycles at seeded journal "
                        "offsets (0 skips the kill-resume leg)")
    p.add_argument("--clients", type=int, default=4,
                   help="request streams (requests are submitted "
                        "sequentially; concurrency comes from the serve "
                        "tier itself)")
    p.add_argument("--requests-per-client", type=int, default=2)
    p.add_argument("--nodes", type=int, default=500,
                   help="vertices per generated request (>=~300 lands "
                        "in the batched shape ladder so the dispatch "
                        "points are exercised)")
    p.add_argument("--degree", type=int, default=6)
    p.add_argument("--batch-max", type=int, default=4)
    p.add_argument("--seed", type=int, default=0,
                   help="master seed: schedules AND kill offsets derive "
                        "from it deterministically")
    p.add_argument("--max-faults", type=int, default=3)
    p.add_argument("--dispatch-timeout", type=float, default=3.0,
                   help="dispatch watchdog deadline for the stacks under "
                        "test (injected hangs must recover through it)")
    p.add_argument("--max-lane-aborts", type=int, default=3)
    p.add_argument("--mesh-devices", type=str, default=None,
                   metavar="auto|N",
                   help="run leg 1's serving stack with the lane axis "
                        "sharded over the local devices (the serve "
                        "CLI's --mesh-devices) — proves fault recovery "
                        "(quarantine, watchdog rebuild, reseat) "
                        "composes with sharding")
    p.add_argument("--deadline", type=float, default=180.0,
                   help="per-leg hard deadline; a run past it is a "
                        "chaos failure (hang)")
    p.add_argument("--report", default="chaos_serve_report.json")
    p.add_argument("--workdir", default=None)
    p.add_argument("--keep-workdir", action="store_true")
    args = p.parse_args(argv)

    reqs = [_request_doc(args.nodes, args.degree,
                         seed=c * 10_000 + r)
            for c in range(args.clients)
            for r in range(args.requests_per_client)]
    print(f"# chaos_serve: {len(reqs)} requests V={args.nodes} "
          f"deg={args.degree} seed={args.seed} schedules={args.schedules} "
          f"kills={args.kills}", file=sys.stderr)
    baseline = _baseline_colors(args, reqs)
    print(f"# chaos_serve: fault-free baseline captured "
          f"({len(baseline)} colorings)", file=sys.stderr)

    schedules = []
    for i in range(args.schedules):
        entry = _run_schedule(i, args, reqs, baseline)
        schedules.append(entry)
        print(f"# [{i}] {entry['outcome']:<12} fired={entry['fired']} "
              f"cover={entry['must_cover']} spec={entry['spec']}",
              file=sys.stderr)

    kill_resume = None
    if args.kills > 0:
        kill_resume = _run_kill_resume(args, reqs, baseline)
        print(f"# kill-resume: {kill_resume['outcome']} "
              f"kills={kill_resume['kills']}/"
              f"{kill_resume['kills_planned']} "
              f"restarts={kill_resume['restarts']}", file=sys.stderr)

    ok = sum(1 for e in schedules if e["outcome"] == "ok")
    structured = sum(1 for e in schedules if e["outcome"] == "structured")
    failed = len(schedules) - ok - structured
    if kill_resume is not None:
        if kill_resume["outcome"] == "ok":
            ok += 1
        else:
            failed += 1
    report = {
        "chaos_serve_report_version": CHAOS_SERVE_REPORT_VERSION,
        "config": {"schedules": args.schedules, "kills": args.kills,
                   "clients": args.clients,
                   "requests_per_client": args.requests_per_client,
                   "nodes": args.nodes, "degree": args.degree,
                   "seed": args.seed, "batch_max": args.batch_max,
                   "dispatch_timeout": args.dispatch_timeout,
                   "max_lane_aborts": args.max_lane_aborts},
        "schedules": schedules,
        "kill_resume": kill_resume,
        "summary": {"total": len(schedules) + (1 if kill_resume else 0),
                    "ok": ok, "structured": structured, "failed": failed},
    }
    problems = validate_chaos_serve_report(report)
    if problems:
        for prob in problems:
            print(f"# chaos_serve report malformed: {prob}",
                  file=sys.stderr)
        failed += 1
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"chaos_serve": {
        "total": report["summary"]["total"], "ok": ok,
        "structured": structured, "failed": failed,
        "report": args.report}}))
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())

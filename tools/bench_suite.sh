#!/usr/bin/env bash
# Full benchmark battery on the real TPU chip — the numbers PERF.md's
# tables are maintained from. One command so a round (or a reviewer)
# can reproduce every published figure:
#
#   bash tools/bench_suite.sh [outfile]
#
# Each bench.py invocation prints one JSON line (appended to the
# outfile, default PERF_RUNS.jsonl) plus its stderr log. Heavy-tail
# configs compile for minutes on first run (and the axon tunnel compiles
# remotely — no local cache engages). Order: most valuable first, so a
# flaky tunnel still yields the headline and flagship-family numbers.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-PERF_RUNS.jsonl}"

# one init-time bound everywhere (integer seconds): the preflight gate
# and every run's in-process watchdog tolerate the same degraded-tunnel
# init time; the preflight adds slack for the cold `import jax` that
# bench.py's watchdog deliberately keeps off the clock. The probe is
# re-run here even under bench_when_up.sh (redundant but cheap) so the
# suite stays safe to invoke on its own.
PROBE_TIMEOUT="${DGC_TPU_BENCH_PROBE_TIMEOUT:-300}"
PROBE_INT="${PROBE_TIMEOUT%.*}"
# bench.py bounds post-init work with its own --run-timeout deadline
# (same env var); the timeout(1) wrapper below is a belt-and-braces
# outer bound with enough slack (run + init + import allowance) that
# bench.py's cleaner in-process abort wins
RUN_TIMEOUT="${DGC_TPU_BENCH_RUN_TIMEOUT:-5400}"
RUN_INT="${RUN_TIMEOUT%.*}"
# 0 means "disabled" for both knobs (matching bench.py's contract):
# probe 0 skips the preflight gate, run 0 drops the outer wrapper
if [ "${PROBE_INT:-0}" -gt 0 ]; then
  if ! timeout "$(( PROBE_INT + 60 ))" \
      python -c 'import jax; assert jax.devices()' >/dev/null 2>&1; then
    echo "backend unreachable - battery aborted" | tee -a /dev/stderr >/dev/null
    exit 2
  fi
fi
if [ "${RUN_INT:-0}" -gt 0 ]; then
  OUTER=(timeout "$(( RUN_INT + ${PROBE_INT:-0} + 180 ))")
else
  OUTER=()
fi
export DGC_TPU_BENCH_PROBE_TIMEOUT="$PROBE_TIMEOUT"

FAILS=0
ABORTED=0
run() {
  # everything goes through tee -a: when stderr is a redirected regular
  # file, a plain tee would reopen it with O_TRUNC and wipe the log, and
  # a bare `echo >&2` would write at the shell's own (stale) fd offset,
  # garbling content tee appended after it. Aborted-run records (value
  # null) are kept out of the jsonl the PERF.md tables are built from,
  # but still count as failures in the battery's exit code.
  if [ "$ABORTED" -ne 0 ]; then return 0; fi
  echo "=== $* ===" | tee -a /dev/stderr >/dev/null
  ${OUTER[@]+"${OUTER[@]}"} python bench.py "$@" 2>&1 \
    | tee -a /dev/stderr | grep '^{' | grep -v '"bench_aborted' >> "$OUT"
  local rcs=("${PIPESTATUS[@]}")
  if [ "${rcs[0]}" -ne 0 ] || [ "${rcs[2]}" -ne 0 ] || [ "${rcs[3]}" -ne 0 ]; then
    FAILS=$((FAILS + 1))
    echo "--- run FAILED (rc=${rcs[0]}): $* ---" | tee -a /dev/stderr >/dev/null
  fi
  # 113 = bench.py watchdog abort (ABORT_RC), 124 = outer timeout kill:
  # the tunnel is gone — stop burning the remaining configs against it
  case "${rcs[0]}" in 113|124) ABORTED=1 ;; esac
}

# headline (1M uniform, warm), then the heavy-tail family (BASELINE
# config 5 shapes), then the cheaper configs and the cold start
run
run --gen rmat --nodes 1000000
run --gen rmat --nodes 4000000 --avg-degree 32
run --gen rmat --nodes 4000000 --avg-degree 32 --max-degree 256
run --gen rmat --nodes 200000
run --gen rmat --nodes 500000
run --gen rmat --nodes 1000000 --backend sharded-bucketed   # multi-chip path at mesh=1
run --nodes 100000                   # BASELINE config 3: 100k, one chip
run --include-compile                # headline cold start

if [ "$ABORTED" -ne 0 ]; then
  echo "battery ABORTED mid-run (backend lost); partial JSON lines in $OUT" >&2
  exit 2
fi
if [ "$FAILS" -gt 0 ]; then
  echo "done with $FAILS FAILED run(s); JSON lines in $OUT" >&2
  exit 1
fi
echo "done; JSON lines in $OUT" >&2

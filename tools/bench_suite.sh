#!/usr/bin/env bash
# Full benchmark battery on the real TPU chip — the numbers PERF.md's
# tables are maintained from. One command so a round (or a reviewer)
# can reproduce every published figure:
#
#   bash tools/bench_suite.sh [outfile]
#
# Each bench.py invocation prints one JSON line (appended to the
# outfile, default PERF_RUNS.jsonl) plus its stderr log. Heavy-tail
# configs compile for minutes on first run (and the axon tunnel compiles
# remotely — no local cache engages). Order: most valuable first, so a
# flaky tunnel still yields the headline and flagship-family numbers.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-PERF_RUNS.jsonl}"

run() {
  # everything goes through tee -a: when stderr is a redirected regular
  # file, a plain tee would reopen it with O_TRUNC and wipe the log, and
  # a bare `echo >&2` would write at the shell's own (stale) fd offset,
  # garbling content tee appended after it
  echo "=== $* ===" | tee -a /dev/stderr >/dev/null
  python bench.py "$@" 2>&1 | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
}

# headline (1M uniform, warm), then the heavy-tail family (BASELINE
# config 5 shapes), then the cheaper configs and the cold start
run
run --gen rmat --nodes 1000000
run --gen rmat --nodes 4000000 --avg-degree 32
run --gen rmat --nodes 4000000 --avg-degree 32 --max-degree 256
run --gen rmat --nodes 200000
run --gen rmat --nodes 500000
run --nodes 100000                   # BASELINE config 3: 100k, one chip
run --include-compile                # headline cold start

echo "done; JSON lines in $OUT" >&2

#!/usr/bin/env python
"""Xplane self-time attribution + devclock timing-column cross-check.

The category-attribution machinery ``tools/trace_attempt.py`` grew for
the gather-rate question, factored into a reusable library that consumes
ANY profiler-window artifact (``obs.profiler`` windows, ``--profile-
window`` CLI captures, ``/debug/profile`` grabs, or a raw logdir /
``.xplane.pb``), renders the self-time split (segmented-gather / gather
/ scatter / while-ctrl / copy / other + idle), and — given the run's
manifest — cross-checks the split against the in-kernel devclock timing
column (``obs.devclock``, trajectory col 5), emitting the
``timing_crosscheck`` verdict ``evidence_suite.sh`` has queued since
PR 7. Runnable on CPU today: the CPU plane's self-times and the
callback-based clock share a clock domain, so the CPU verdict calibrates
how much to trust the column before a chip ever sees it.

Verdict rule: ``coverage = in_kernel_ms / xplane_ms`` (the while-loop
supersteps the column times are a SUBSET of the device ops in the trace
— compile-adjacent executions, transfers, and host scaffolding are in
the xplane but not the column, so coverage ≤ ~1 is healthy). The verdict
is ``ok`` when ``lo <= coverage <= hi`` (defaults 0.25/1.25 — the
CPU-measured envelope, PERF.md "Timing-column vs xplane cross-check"),
``divergent`` otherwise: a column reporting more time than the device
executed, or almost none of it, means the clock path cannot be trusted
on that backend.

Usage:
  python tools/xplane_split.py ARTIFACT [--top N]
  python tools/xplane_split.py ARTIFACT --manifest RUN.json \
      [--lo 0.25] [--hi 1.25] [--emit-runlog LOG.jsonl] [--strict]

ARTIFACT: a ``.xplane.pb``, a profiler logdir, or a run manifest whose
``profiles`` slot links one (the last window wins). Prints one JSON
object: the split, plus ``timing_crosscheck`` when a manifest with a
timing column was given. ``--strict`` exits 1 on a divergent verdict;
``--emit-runlog`` appends the verdict event to a JSONL run log
(schema-checked by tools/validate_runlog.py like every other event).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)  # dgc_tpu is not an installed package

_CATEGORIES = (
    # order matters: first match wins
    # the segmented plan's fused gathers carry the ``seg_gather`` scope
    # (ops.segmented_gather.segmented_gather wraps THE gather in
    # jax.named_scope), so their self-time attributes separately from
    # residual small gathers — the on-chip measurement of the plan's rate
    # claim
    ("segmented-gather", re.compile(r"seg_gather", re.I)),
    ("gather", re.compile(r"gather|dynamic-slice(?!-update)|take", re.I)),
    ("scatter", re.compile(r"scatter|dynamic-update-slice", re.I)),
    ("collective", re.compile(r"all-gather|all-reduce|reduce-scatter|"
                              r"collective|permute", re.I)),
    ("copy", re.compile(r"copy|transpose|bitcast|reshape", re.I)),
    ("while-ctrl", re.compile(r"while|condition|tuple|parameter|select-n", re.I)),
    ("sort", re.compile(r"sort", re.I)),
    ("fusion-elementwise", re.compile(r"fusion", re.I)),
)


def _categorize(name: str) -> str:
    for cat, pat in _CATEGORIES:
        if pat.search(name):
            return cat
    return "other"


def _line_self_times(evts: list, into: dict) -> None:
    """Accumulate per-op SELF time (duration minus directly-nested child
    durations) for one trace line into ``into``.

    Trace lines nest events by time containment (a while op spans its body
    ops; on TPU the XLA Ops line nests control flow around fusions), so a
    plain sum double-counts every container. Stack-based interval nesting
    gives exact self-times without hierarchy metadata.
    """
    evts.sort(key=lambda e: (e[0], -e[1]))
    stack: list[list] = []  # [end, name, dur, child_sum]

    def close(upto: float) -> None:
        while stack and stack[-1][0] <= upto:
            end, name, dur, csum = stack.pop()
            into[name] = into.get(name, 0.0) + max(0.0, dur - csum)
            if stack:
                stack[-1][3] += dur

    for off, dur, name in evts:
        close(off)
        stack.append([off + dur, name, dur, 0.0])
    close(float("inf"))


def attribute_xspace(xspace_path: str, top: int = 20) -> dict:
    """Aggregate device-plane op SELF times from one ``.xplane.pb``."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(xspace_path, "rb") as f:
        xs.ParseFromString(f.read())

    # device planes: TPU (axon remote chip) or the host-CPU XLA plane when
    # run off-chip for plumbing tests
    planes = [p for p in xs.planes
              if "/device:" in p.name or "TPU" in p.name]
    if not planes:
        planes = [p for p in xs.planes if ":CPU" in p.name]
    # host/runtime scaffolding that shows up when the fallback picks a CPU
    # plane (python frames, PjRt/thunk wrappers, transfer/marker events) —
    # never real device ops. The module/step summary lines on TPU planes
    # span the whole execution and are skipped wholesale below.
    noise = re.compile(r"^\$|^PjRt|^Thunk|^PjitFunction|^XlaModule|"
                       r"^DevicePut|^np\.|^end: |^jit_|trace|__exit__")
    per_op: dict[str, float] = {}
    span_lo, span_hi = None, 0
    for plane in planes:
        meta = plane.event_metadata
        smeta = plane.stat_metadata
        lines = plane.lines

        def scoped_name(ev, name):
            """Named-scope attribution: the lowered instruction NAME never
            carries ``jax.named_scope`` labels — they live in the event's
            op_name/tf_op stat (and in the event metadata's display name
            on some backends). The segmented plan wraps its fused gather
            in ``seg_gather``; prefix the op so the category split sees
            it."""
            hay = [meta[ev.metadata_id].display_name]
            for st in ev.stats:
                sm = smeta.get(st.metadata_id)
                if sm is not None and sm.name in (
                        "tf_op", "op_name", "hlo_op", "long_name"):
                    hay.append(st.str_value
                               or (smeta.get(st.ref_value).name
                                   if st.ref_value else ""))
            if any(h and "seg_gather" in h for h in hay):
                return "seg_gather/" + name
            return name

        # TPU device planes carry an explicit "XLA Ops" line; when present
        # it is the only line with real per-op events. On the CPU
        # fallback plane the executed ops live on the ``tf_XLA*`` thread
        # lines — the ``python`` frame line and the llvm-codegen thread
        # carry compile passes (JitCompiler/lower_*/simplify-*) that
        # would otherwise masquerade as device time in a cold window
        op_lines = [l for l in lines if l.name == "XLA Ops"] or [
            l for l in lines if l.name.startswith("tf_XLA")] or [
            l for l in lines if l.name not in ("XLA Modules", "Steps",
                                               "Framework Ops")]
        for line in op_lines:
            evts = []
            for ev in line.events:
                name = meta[ev.metadata_id].name
                if noise.search(name):
                    continue
                dur = ev.duration_ps / 1e12
                t0 = line.timestamp_ns * 1e-9 + ev.offset_ps / 1e12
                evts.append((t0, dur, scoped_name(ev, name)))
                span_lo = t0 if span_lo is None else min(span_lo, t0)
                span_hi = max(span_hi, t0 + dur)
            _line_self_times(evts, per_op)

    cats: dict[str, float] = {}
    for name, dur in per_op.items():
        cat = _categorize(name)
        cats[cat] = cats.get(cat, 0.0) + dur
    total = sum(per_op.values())
    span = (span_hi - span_lo) if span_lo is not None else 0.0
    top_ops = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    return {
        "planes": [p.name for p in planes],
        "device_op_time_s": round(total, 4),
        "trace_span_s": round(span, 4),
        "gap_time_s": round(max(0.0, span - total), 4),
        "categories_s": {k: round(v, 4)
                         for k, v in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"op": n, "s": round(d, 4)} for n, d in top_ops],
    }


# ---------------------------------------------------------------------------
# artifact resolution + cross-check
# ---------------------------------------------------------------------------

def resolve_artifact(path: str) -> str:
    """ARTIFACT → a ``.xplane.pb`` path. Accepts the file itself, a
    profiler logdir, or a run manifest whose ``profiles`` slot links a
    window (last window with an artifact wins). Raises ValueError."""
    if path.endswith(".xplane.pb"):
        return path
    if os.path.isdir(path):
        found = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                                 recursive=True), key=os.path.getmtime)
        if not found:
            raise ValueError(f"no .xplane.pb under logdir {path}")
        return found[-1]
    if path.endswith(".json"):
        doc = json.loads(open(path).read())
        for prof in reversed(doc.get("profiles") or []):
            xp = prof.get("xplane")
            if xp:
                if not os.path.isabs(xp):
                    xp = os.path.join(os.path.dirname(path) or ".", xp)
                return xp
        raise ValueError(f"manifest {path} links no profile artifact")
    raise ValueError(f"not an .xplane.pb, logdir, or manifest: {path}")


def in_kernel_ms(doc: dict) -> tuple:
    """(total_ms, attempts_with_column, supersteps_timed) summed over the
    manifest's trajectory timing columns (``step_us``, −1 = unwritten)."""
    total_us = 0
    attempts = 0
    steps = 0
    for att in doc.get("attempts") or []:
        traj = att.get("trajectory") or {}
        col = [u for u in (traj.get("step_us") or []) if u >= 0]
        if col:
            attempts += 1
            steps += len(col)
            total_us += sum(col)
    return total_us / 1e3, attempts, steps


def crosscheck(split: dict, kernel_ms: float, *, attempts: int = 0,
               supersteps: int = 0, lo: float = 0.25, hi: float = 1.25,
               xplane: str | None = None) -> dict:
    """The ``timing_crosscheck`` verdict fields (obs.schema)."""
    xp_ms = split.get("device_op_time_s", 0.0) * 1e3
    coverage = (kernel_ms / xp_ms) if xp_ms > 0 else None
    ok = coverage is not None and lo <= coverage <= hi
    return {
        "in_kernel_ms": round(kernel_ms, 3),
        "xplane_ms": round(xp_ms, 3),
        "coverage": round(coverage, 4) if coverage is not None else None,
        "lo": lo, "hi": hi,
        "verdict": "ok" if ok else "divergent",
        "attempts": attempts, "supersteps": supersteps,
        "xplane": xplane,
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("artifact",
                   help=".xplane.pb, profiler logdir, or run manifest")
    p.add_argument("--manifest", type=str, default=None,
                   help="run manifest with trajectory timing columns "
                        "(--superstep-timing) to cross-check against; "
                        "defaults to ARTIFACT when that is a manifest")
    p.add_argument("--top", type=int, default=20,
                   help="top-N ops in the split (default 20)")
    p.add_argument("--lo", type=float, default=0.25,
                   help="coverage lower bound for an ok verdict")
    p.add_argument("--hi", type=float, default=1.25,
                   help="coverage upper bound for an ok verdict")
    p.add_argument("--emit-runlog", type=str, default=None, metavar="JSONL",
                   help="append the timing_crosscheck event to this run log")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on a divergent verdict")
    args = p.parse_args(argv)

    manifest_path = args.manifest
    if manifest_path is None and args.artifact.endswith(".json"):
        manifest_path = args.artifact
    try:
        xplane = resolve_artifact(args.artifact)
        split = attribute_xspace(xplane, args.top)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    out = dict(split, xplane=xplane)
    verdict = None
    if manifest_path is not None:
        try:
            doc = json.loads(open(manifest_path).read())
        except (OSError, ValueError) as e:
            print(f"error: cannot load manifest {manifest_path}: {e}",
                  file=sys.stderr)
            return 2
        kernel_ms, attempts, steps = in_kernel_ms(doc)
        if attempts == 0:
            print(f"error: {manifest_path} has no trajectory timing "
                  f"column (run with --superstep-timing)", file=sys.stderr)
            return 2
        verdict = crosscheck(split, kernel_ms, attempts=attempts,
                             supersteps=steps, lo=args.lo, hi=args.hi,
                             xplane=xplane)
        out["timing_crosscheck"] = verdict
        if args.emit_runlog:
            from dgc_tpu.obs.events import RunLogger

            logger = RunLogger(jsonl_path=args.emit_runlog, echo=False)
            logger.event("timing_crosscheck", **verdict)
            logger.close()

    print(json.dumps(out))
    if args.strict and verdict is not None and verdict["verdict"] != "ok":
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Schema-check a JSONL run log against ``dgc_tpu.obs.schema``.

Exits nonzero on any unknown event kind, unknown field, missing required
field, wrong field type, or unparseable line — the drift guard the obs
tests run over every log they produce, so an event emitted anywhere in the
codebase without a matching schema entry fails CI instead of silently
rotting the contract.

``span`` events additionally get structural validation (the tracing
contract, ``dgc_tpu.obs.trace``): a child span must begin after its
parent began, no span may begin or end twice, every end must match an
open begin, and every opened span must be closed by end of log. A torn
trailing line (a live log caught mid-write, no newline yet) is tolerated
— the tail-follower convention — but torn lines elsewhere still fail.

Usage: python tools/validate_runlog.py RUNLOG.jsonl [...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.obs.schema import validate_record  # noqa: E402


class _SpanChecker:
    """Structural span invariants over one log's event order."""

    def __init__(self):
        self._open: dict = {}    # (trace, span) -> name
        self._begun: set = set()

    def feed(self, record: dict) -> list[str]:
        problems: list[str] = []
        trace, span = record.get("trace"), record.get("span")
        key = (trace, span)
        ph = record.get("ph")
        name = record.get("name")
        if ph == "B":
            if key in self._begun:
                problems.append(
                    f"span {span} ({name}) in trace {trace} begun twice")
            self._begun.add(key)
            self._open[key] = name
            parent = record.get("parent")
            if parent is not None and (trace, parent) not in self._begun:
                problems.append(
                    f"span {span} ({name}) begins before its parent "
                    f"{parent} in trace {trace}")
        elif ph == "E":
            if key not in self._open:
                problems.append(
                    f"span {span} ({name}) in trace {trace} "
                    + ("ended twice" if key in self._begun
                       else "ends without a begin"))
            else:
                del self._open[key]
        else:
            problems.append(f"span {span}: unknown ph {ph!r} (want B|E)")
        return problems

    def finish(self) -> list[str]:
        return [f"span {span} ({name}) in trace {trace} never closed"
                for (trace, span), name in sorted(
                    self._open.items(), key=lambda kv: str(kv[0]))]


def _semantic_problems(record: dict) -> list[str]:
    """Value-level enforcement beyond the type schema for the PR 11
    diagnose-after-the-fact kinds: counts non-negative, verdict strings
    from the closed vocabulary, and a regression verdict must carry the
    baseline it regressed against."""
    kind = record.get("event")
    problems: list[str] = []
    if kind == "flightrec_dump":
        if isinstance(record.get("records"), int) and record["records"] < 0:
            problems.append(f"flightrec_dump: records {record['records']} < 0")
        if isinstance(record.get("dropped_spans"), int) \
                and record["dropped_spans"] < 0:
            problems.append("flightrec_dump: dropped_spans < 0")
    elif kind == "profile_window":
        if isinstance(record.get("seconds"), (int, float)) \
                and record["seconds"] < 0:
            problems.append(f"profile_window: seconds {record['seconds']} < 0")
    elif kind == "timing_crosscheck":
        if record.get("verdict") not in ("ok", "divergent"):
            problems.append(
                f"timing_crosscheck: verdict {record.get('verdict')!r} "
                f"not in ('ok', 'divergent')")
    elif kind == "perf_regression":
        if record.get("regression") is True \
                and record.get("baseline_median") is None:
            problems.append("perf_regression: regression=true without a "
                            "baseline_median")
    # network front door (serve.netfront, PR 12): reject reasons come
    # from the admission layer's closed vocabulary, retry hints and
    # drain counts are non-negative, and tenants are never empty —
    # keeping the 429/drain contract machine-checkable end to end
    elif kind in ("net_admit", "net_reject"):
        if record.get("tenant") == "":
            problems.append(f"{kind}: empty tenant")
        if kind == "net_reject":
            from dgc_tpu.serve.netfront.admission import REJECT_REASONS

            if record.get("reason") not in REJECT_REASONS:
                problems.append(
                    f"net_reject: reason {record.get('reason')!r} not in "
                    f"{REJECT_REASONS}")
            retry = record.get("retry_after_s")
            if isinstance(retry, (int, float)) and not isinstance(
                    retry, bool) and retry < 0:
                problems.append(
                    f"net_reject: retry_after_s {retry} < 0")
        if kind == "net_admit" \
                and isinstance(record.get("priority"), int) \
                and record["priority"] < 0:
            problems.append(
                f"net_admit: priority {record['priority']} < 0")
    elif kind == "net_drain":
        for fieldname in ("in_flight", "queued", "completed", "failed"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"net_drain: {fieldname} {v} < 0")
    # crash-safe serve tier: journal recovery actions come from a closed
    # vocabulary, rebuild reasons likewise, and every recovery/rebuild
    # count is non-negative — the kill-resume chaos harness's artifacts
    # stay machine-checkable end to end
    elif kind == "net_recover":
        if record.get("action") not in ("restored", "replayed",
                                        "replay_failed", "summary"):
            problems.append(
                f"net_recover: action {record.get('action')!r} not in "
                f"('restored', 'replayed', 'replay_failed', 'summary')")
        for fieldname in ("records", "restored", "replayed", "failed",
                          "namespaces", "foreign"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"net_recover: {fieldname} {v} < 0")
    # content-addressed result cache + single-flight coalescing: cache
    # actions come from a closed vocabulary, the hit tier (when named)
    # is mem/disk, a coalesced follower always names its leader, and
    # tenants are never empty — the cache A/B and chaos_fleet
    # ``--result-cache`` artifacts stay machine-checkable end to end
    elif kind == "net_cache":
        action = record.get("action")
        if action not in ("hit", "miss", "coalesced", "store",
                          "promote", "evict", "recover_fill"):
            problems.append(
                f"net_cache: action {action!r} not in "
                f"('hit', 'miss', 'coalesced', 'store', 'promote', "
                f"'evict', 'recover_fill')")
        if record.get("tenant") == "":
            problems.append("net_cache: empty tenant")
        source = record.get("source")
        if source is not None and source not in ("mem", "disk"):
            problems.append(
                f"net_cache: source {source!r} not in ('mem', 'disk')")
        if action == "coalesced" and not record.get("cached_from"):
            problems.append(
                "net_cache: coalesced follower without a cached_from "
                "leader ticket")
        # disk-GC evictions name their bound and a non-negative size
        if action == "evict" \
                and record.get("reason") not in ("ttl", "max_bytes"):
            problems.append(
                f"net_cache: evict reason {record.get('reason')!r} not "
                f"in ('ttl', 'max_bytes')")
        nbytes = record.get("bytes")
        if isinstance(nbytes, int) and not isinstance(nbytes, bool) \
                and nbytes < 0:
            problems.append(f"net_cache: bytes {nbytes} < 0")
        v = record.get("v")
        if isinstance(v, int) and not isinstance(v, bool) and v < 0:
            problems.append(f"net_cache: v {v} < 0")
    # speculative minimal-k (serve.speculate): cancellation reasons and
    # sites come from closed vocabularies, budgets are >= 1 (a
    # speculative seat below k=1 can never be claimed), and wasted
    # supersteps are non-negative — the speculation A/B artifacts stay
    # machine-checkable end to end
    elif kind in ("spec_seated", "spec_win", "spec_cancelled"):
        k = record.get("k")
        if isinstance(k, int) and not isinstance(k, bool) and k < 1:
            problems.append(f"{kind}: k {k} < 1")
        if kind == "spec_seated":
            lane = record.get("lane")
            if isinstance(lane, int) and not isinstance(lane, bool) \
                    and lane < 0:
                problems.append(f"spec_seated: lane {lane} < 0")
        elif kind == "spec_cancelled":
            if record.get("where") not in ("queue", "lane", "done"):
                problems.append(
                    f"spec_cancelled: where {record.get('where')!r} "
                    f"not in ('queue', 'lane', 'done')")
            if not record.get("reason"):
                problems.append("spec_cancelled: empty reason")
            wasted = record.get("wasted_steps")
            if isinstance(wasted, int) and not isinstance(wasted, bool) \
                    and wasted < 0:
                problems.append(
                    f"spec_cancelled: wasted_steps {wasted} < 0")
    # closed-loop robustness controllers (PR 17): probe actions and
    # brownout transitions come from closed vocabularies, backoffs and
    # levels stay in range — chaos_fleet's artifacts stay
    # machine-checkable end to end
    elif kind == "mesh_probe":
        if record.get("action") not in ("probed", "restore_requested"):
            problems.append(
                f"mesh_probe: action {record.get('action')!r} not in "
                f"('probed', 'restore_requested')")
        if record.get("action") == "restore_requested" \
                and record.get("ok") is not True:
            problems.append("mesh_probe: restore_requested with ok != "
                            "true (restore armed off a failed canary?)")
        backoff = record.get("backoff_s")
        if isinstance(backoff, (int, float)) \
                and not isinstance(backoff, bool):
            if backoff < 0:
                problems.append(f"mesh_probe: backoff_s {backoff} < 0")
            if record.get("ok") is True:
                problems.append(
                    "mesh_probe: backoff_s on a successful probe")
        device = record.get("device")
        if isinstance(device, int) and not isinstance(device, bool) \
                and device < 0:
            problems.append(f"mesh_probe: device {device} < 0")
    elif kind == "net_brownout":
        action, level = record.get("action"), record.get("level")
        if action not in ("shed", "restore"):
            problems.append(
                f"net_brownout: action {action!r} not in "
                f"('shed', 'restore')")
        if isinstance(level, int) and not isinstance(level, bool):
            if level < 0:
                problems.append(f"net_brownout: level {level} < 0")
            if action == "shed" and level < 1:
                problems.append(
                    "net_brownout: shed transition to level < 1")
    # failure-domain plane: a degrade must shrink the mesh (and a
    # restore grow it back), device counts stay >= 1 (devices_after 1 =
    # collapsed to the unsharded path), and every evacuation count is
    # non-negative — the chaos_mesh artifacts stay machine-checkable
    elif kind in ("mesh_degrade", "mesh_restore"):
        before, after = record.get("devices_before"), record.get(
            "devices_after")
        if isinstance(before, int) and isinstance(after, int):
            if after < 1 or before < 1:
                problems.append(f"{kind}: device counts must be >= 1 "
                                f"({before} -> {after})")
            elif kind == "mesh_degrade" and after > before:
                # a degrade may KEEP the size (8 devices lose one ->
                # pow2 4; a second loss leaves 6 survivors -> still 4,
                # over a different survivor set) but never grow it
                problems.append(
                    f"mesh_degrade: devices_after {after} above "
                    f"devices_before {before}")
            elif kind == "mesh_restore" and after <= before:
                problems.append(
                    f"mesh_restore: devices_after {after} not above "
                    f"devices_before {before}")
        for fieldname in ("reseated", "quarantined"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"{kind}: {fieldname} {v} < 0")
    elif kind == "lane_rebuild":
        if record.get("reason") not in ("abort", "hang"):
            problems.append(
                f"lane_rebuild: reason {record.get('reason')!r} not in "
                f"('abort', 'hang')")
        for fieldname in ("reseated", "quarantined", "aborts_max"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"lane_rebuild: {fieldname} {v} < 0")
    # per-tenant usage metering (obs.usage): every lifecycle count is
    # non-negative, a negative in_flight means a ticket was terminal
    # twice, and the source comes from the closed live/journal
    # vocabulary — the billing rows stay machine-checkable
    elif kind == "usage_rollup":
        for fieldname in ("admitted", "delivered", "failed", "aborted",
                          "in_flight", "vertices", "vertex_supersteps"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"usage_rollup: {fieldname} {v} < 0")
        src = record.get("source")
        if src is not None and src not in ("live", "journal"):
            problems.append(
                f"usage_rollup: source {src!r} not in "
                f"('live', 'journal')")
    # continuous SLO burn-rate telemetry (obs.timeseries): a burn is
    # meaningless without a positive evaluation window, burns are
    # non-negative, and the objective comes from the evaluator's closed
    # vocabulary (slo_check threshold keys x quantiles)
    elif kind == "slo_burn":
        w = record.get("window_s")
        if isinstance(w, (int, float)) and not isinstance(w, bool) \
                and w <= 0:
            problems.append(f"slo_burn: window_s {w} <= 0 "
                            f"(burn needs a window)")
        for fieldname in ("burn", "fast_burn", "slow_burn"):
            v = record.get(fieldname)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v < 0:
                problems.append(f"slo_burn: {fieldname} {v} < 0")
        obj = record.get("objective")
        allowed = ("failure_rate",
                   "service_ms_p50", "service_ms_p95", "service_ms_p99",
                   "queue_ms_p50", "queue_ms_p95", "queue_ms_p99")
        if isinstance(obj, str) and obj not in allowed:
            problems.append(
                f"slo_burn: objective {obj!r} not in {allowed}")
    # multi-device serve tier (--mesh-devices): the lane mesh is ≥ 2
    # devices when reported at all (size 1 is the unsharded path and
    # emits no mesh fields), and the per-device occupancy series has
    # one [0, 1] entry per mesh device
    if kind == "serve_summary":
        for fieldname in ("mesh_degrades", "lanes_evacuated"):
            v = record.get(fieldname)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                problems.append(f"serve_summary: {fieldname} {v} < 0")
    if kind in ("serve_start", "serve_slice", "serve_batch",
                "serve_summary"):
        mesh_n = record.get("mesh_devices")
        if mesh_n is not None and isinstance(mesh_n, int) and mesh_n < 2:
            problems.append(f"{kind}: mesh_devices {mesh_n} < 2 (the "
                            f"unsharded path emits no mesh fields)")
        occ = record.get("device_occupancy")
        if occ is not None and isinstance(occ, list):
            if isinstance(mesh_n, int) and len(occ) != mesh_n:
                problems.append(
                    f"{kind}: device_occupancy has {len(occ)} entries "
                    f"for mesh_devices={mesh_n}")
            for x in occ:
                if not isinstance(x, (int, float)) or isinstance(x, bool) \
                        or x < 0 or x > 1:
                    problems.append(
                        f"{kind}: device_occupancy entry {x!r} outside "
                        f"[0, 1]")
                    break
    return problems


def validate_file(path: str) -> list[str]:
    """All schema and span-structure problems in one JSONL log, prefixed
    with line numbers."""
    problems: list[str] = []
    spans = _SpanChecker()
    with open(path) as fh:
        raw = fh.read()
    lines = raw.split("\n")
    torn_tail = not raw.endswith("\n")
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError as e:
            if torn_tail and lineno == len(lines):
                continue   # live log caught mid-write; writer re-emits
            problems.append(f"{path}:{lineno}: unparseable JSON: {e}")
            continue
        for problem in validate_record(record):
            problems.append(f"{path}:{lineno}: {problem}")
        if isinstance(record, dict):
            for problem in _semantic_problems(record):
                problems.append(f"{path}:{lineno}: {problem}")
        if isinstance(record, dict) and record.get("event") == "span":
            for problem in spans.feed(record):
                problems.append(f"{path}:{lineno}: {problem}")
    for problem in spans.finish():
        problems.append(f"{path}: {problem}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", help="JSONL run log(s)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the per-file OK lines")
    args = p.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            problems = validate_file(path)
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 2
            continue
        if problems:
            rc = 1
            for problem in problems:
                print(problem, file=sys.stderr)
        elif not args.quiet:
            print(f"{path}: OK")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Schema-check a JSONL run log against ``dgc_tpu.obs.schema``.

Exits nonzero on any unknown event kind, unknown field, missing required
field, wrong field type, or unparseable line — the drift guard the obs
tests run over every log they produce, so an event emitted anywhere in the
codebase without a matching schema entry fails CI instead of silently
rotting the contract.

Usage: python tools/validate_runlog.py RUNLOG.jsonl [...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.obs.schema import validate_record  # noqa: E402


def validate_file(path: str) -> list[str]:
    """All schema problems in one JSONL log, prefixed with line numbers."""
    problems: list[str] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError as e:
                problems.append(f"{path}:{lineno}: unparseable JSON: {e}")
                continue
            for problem in validate_record(record):
                problems.append(f"{path}:{lineno}: {problem}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", help="JSONL run log(s)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the per-file OK lines")
    args = p.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            problems = validate_file(path)
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 2
            continue
        if problems:
            rc = 1
            for problem in problems:
                print(problem, file=sys.stderr)
        elif not args.quiet:
            print(f"{path}: OK")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

"""Decompose the staged kernels' effective gather rate on the real chip.

PERF.md's audits price sweeps in element gathers, and the conversion to
seconds uses an *effective* ~45-50M lookups/s measured end-to-end — half
the raw 100-140M/s large-gather rate. This probe isolates where the
factor goes by timing, on device, inside a ``lax.while_loop`` (the
production setting — one iteration per superstep, loop-carried
dependencies):

1. the leaf-stage shape: [4096, 256] gather from a [1M] table;
2. the mid-stage shape: [65536, 64];
3. the stage-0-range shape: [262144, 40] (the v/4 stage's dominant range);
4. a hub pruned chain: [4096, 256] + [1024, 512] + [128, 2048] per
   iteration (one superstep's hub work, sequential deps via the carry);
5. one loop-free 32M-element flat gather (``flat_reference_32M`` — the
   large-gather rate the loop cases are compared against, rate vs rate);
6. an empty while_loop (pure per-iteration overhead).

Usage (tunnel must be up): python tools/rate_probe.py [iters]
Prints one JSON line per case: {case, iters, total_elems, seconds,
rate_M_per_s, per_iter_us}.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args):
    r = fn(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = fn(*args)
    jax.block_until_ready(r)
    return time.perf_counter() - t0


def loop_gather(table, idx, iters):
    """while_loop of gathers with a carried dependency (sum feeds the next
    iteration's index offset mod V — defeats batching across iterations,
    like a real superstep's state dependence)."""
    v = table.shape[0]

    def body(c):
        i, acc = c
        g = table[(idx + acc % v) % v]
        return i + 1, acc + jnp.sum(g)

    def cond(c):
        return c[0] < iters

    return jax.lax.while_loop(cond, body, (jnp.int32(0), jnp.int32(0)))[1]


def main() -> int:
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    v = 1_000_000
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, 2**30, v, dtype=np.int64).astype(np.int32))
    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind} ({dev.platform})", file=sys.stderr)

    shapes = {
        "leaf_4096x256": (4096, 256),
        "mid_65536x64": (65536, 64),
        "stage0_262144x40": (262144, 40),
    }
    out = []
    for name, (r, w) in shapes.items():
        idx = jnp.asarray(rng.integers(0, v, (r, w), dtype=np.int64).astype(np.int32))
        f = jax.jit(loop_gather, static_argnums=2)
        sec = timed(f, table, idx, iters)
        elems = r * w * iters
        out.append(dict(case=f"loop_{name}", iters=iters, total_elems=elems,
                        seconds=round(sec, 4),
                        rate_M_per_s=round(elems / sec / 1e6, 1),
                        per_iter_us=round(sec / iters * 1e6, 1)))

    # one loop-free large gather: the reference rate (rate vs rate — the
    # loop cases above carry different total volumes by design)
    flat_idx = jnp.asarray(
        rng.integers(0, v, 32_000_000, dtype=np.int64).astype(np.int32))
    g = jax.jit(lambda t, i: jnp.sum(t[i]))
    sec1 = timed(g, table, flat_idx)
    out.append(dict(case="flat_reference_32M", iters=1,
                    total_elems=int(flat_idx.size),
                    seconds=round(sec1, 4),
                    rate_M_per_s=round(flat_idx.size / sec1 / 1e6, 1),
                    per_iter_us=round(sec1 * 1e6, 1)))

    # hub chain: three dependent gathers per iteration (one superstep's hub)
    idxs = [jnp.asarray(rng.integers(0, v, s, dtype=np.int64).astype(np.int32))
            for s in ((4096, 256), (1024, 512), (128, 2048))]

    def chain(table, i0, i1, i2, iters):
        def body(c):
            i, acc = c
            a = jnp.sum(table[(i0 + acc % 7) % v])
            b = jnp.sum(table[(i1 + a % 5) % v])
            d = jnp.sum(table[(i2 + b % 3) % v])
            return i + 1, acc + d

        return jax.lax.while_loop(lambda c: c[0] < iters, body,
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(chain, static_argnums=4)
    sec = timed(f, table, *idxs, iters)
    elems = sum(int(np.prod(s.shape)) for s in idxs) * iters
    out.append(dict(case="loop_hub_chain3", iters=iters, total_elems=elems,
                    seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    # switch-routed gather: the unified pipeline dispatches each
    # superstep's stage body through lax.switch — measures whether the
    # routing itself (branch selection, no fusion across the switch)
    # taxes the same gather the plain loop case runs. Compare
    # loop_switch3_mid vs loop_mid_65536x64: same shape, same volume.
    idx_mid = jnp.asarray(
        rng.integers(0, v, (65536, 64), dtype=np.int64).astype(np.int32))

    def switched(table, idx, iters):
        def mk(off):
            def br(acc):
                return jnp.sum(table[(idx + (acc + off) % v) % v])
            return br

        def body(c):
            i, acc = c
            s = jnp.sum(table[(idx_mid[0] + acc) % v]) % 3  # data-dep route
            g = jax.lax.switch(s, [mk(0), mk(1), mk(2)], acc)
            return i + 1, acc + g

        return jax.lax.while_loop(lambda c: c[0] < iters, body,
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(switched, static_argnums=2)
    sec = timed(f, table, idx_mid, iters)
    elems = 65536 * 64 * iters
    out.append(dict(case="loop_switch3_mid", iters=iters, total_elems=elems,
                    seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    # many-small vs one-large at EQUAL volume: eight dependent 512x64
    # gathers per iteration vs one 4096x64 — isolates small-gather
    # underutilization (the heavy-tail stage/hub shapes are small)
    idx_small = [jnp.asarray(rng.integers(0, v, (512, 64),
                                          dtype=np.int64).astype(np.int32))
                 for _ in range(8)]

    def many_small(table, iters, *idxs):
        def body(c):
            i, acc = c
            for ix in idxs:  # dependent chain, like sequential hub buckets
                acc = acc + jnp.sum(table[(ix + acc % 3) % v])
            return i + 1, acc

        return jax.lax.while_loop(lambda c: c[0] < iters, body,
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(many_small, static_argnums=1)
    sec = timed(f, table, iters, *idx_small)
    elems = 8 * 512 * 64 * iters
    out.append(dict(case="loop_8x512x64_chain", iters=iters,
                    total_elems=elems, seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    idx_one = jnp.asarray(rng.integers(0, v, (4096, 64),
                                       dtype=np.int64).astype(np.int32))
    f = jax.jit(loop_gather, static_argnums=2)
    sec = timed(f, table, idx_one, iters)
    elems = 4096 * 64 * iters
    out.append(dict(case="loop_4096x64_single", iters=iters,
                    total_elems=elems, seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    # segmented plan A/B at EQUAL volume: the staged superstep's exact
    # shapes — six width-ranged gathers (the pre-segmentation schedule:
    # one dependent gather per range) vs ONE flat segmented gather over
    # the identical index set (ops.segmented_gather). The ratio of these
    # two cases IS the rate claim of the segmented-plan PR; run on chip
    # the moment the tunnel returns (tools/evidence_suite.sh queues it).
    range_shapes = ((512, 40), (1024, 48), (1024, 56), (512, 64),
                    (512, 128), (512, 256))
    idx_ranges = [jnp.asarray(rng.integers(0, v, s, dtype=np.int64)
                              .astype(np.int32)) for s in range_shapes]

    def range_chain(table, iters, *idxs):
        def body(c):
            i, acc = c
            for ix in idxs:   # one gather per width range, dependent
                acc = acc + jnp.sum(table[(ix + acc % 3) % v])
            return i + 1, acc

        return jax.lax.while_loop(lambda c: c[0] < iters, body,
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(range_chain, static_argnums=1)
    sec = timed(f, table, iters, *idx_ranges)
    vol = sum(r * w for r, w in range_shapes)
    elems = vol * iters
    out.append(dict(case="loop_6range_chain", iters=iters, total_elems=elems,
                    seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    idx_seg = jnp.concatenate([ix.reshape(-1) for ix in idx_ranges])

    def seg_gather(table, idx, iters):
        def body(c):
            i, acc = c
            with jax.named_scope("seg_gather"):
                g = table[(idx + acc % v) % v]
            return i + 1, acc + jnp.sum(g)

        return jax.lax.while_loop(lambda c: c[0] < iters, body,
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(seg_gather, static_argnums=2)
    sec = timed(f, table, idx_seg, iters)
    out.append(dict(case="loop_segmented_1flat", iters=iters,
                    total_elems=elems, seconds=round(sec, 4),
                    rate_M_per_s=round(elems / sec / 1e6, 1),
                    per_iter_us=round(sec / iters * 1e6, 1)))

    # empty loop: pure per-iteration overhead
    def empty(iters):
        return jax.lax.while_loop(lambda c: c[0] < iters,
                                  lambda c: (c[0] + 1, c[1] + 1),
                                  (jnp.int32(0), jnp.int32(0)))[1]

    f = jax.jit(empty, static_argnums=0)
    sec = timed(f, iters * 10)
    out.append(dict(case="empty_loop", iters=iters * 10, total_elems=0,
                    seconds=round(sec, 5), rate_M_per_s=0.0,
                    per_iter_us=round(sec / (iters * 10) * 1e6, 2)))

    for o in out:
        print(json.dumps(o))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Render a dgc_tpu run artifact into a human-readable sweep report.

Input: a run manifest (``dgc-tpu --run-manifest out.json``) or a raw JSONL
run log (``--log-json``) — a JSONL log is replayed through the same
``RunManifest`` sink the CLI uses, so both inputs render identically.

Usage: python tools/report_run.py MANIFEST_OR_JSONL [--width N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.obs.manifest import RunManifest, load_manifest  # noqa: E402

_BARS = " ▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 48) -> str:
    """Down-sampled unicode sparkline of a count series."""
    if not values:
        return ""
    if len(values) > width:
        stride = len(values) / width
        values = [values[int(i * stride)] for i in range(width)]
    peak = max(max(values), 1)
    return "".join(_BARS[min(int(v / peak * (len(_BARS) - 1)), len(_BARS) - 1)]
                   for v in values)


def _load(path: str) -> dict:
    if path.endswith(".jsonl"):
        manifest = RunManifest()
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    manifest(json.loads(line))
        return manifest.doc
    return load_manifest(path)


def render(doc: dict, width: int = 48) -> str:
    out = []
    add = out.append
    add("=== dgc_tpu run report ===")
    g = doc.get("graph")
    if g:
        add("graph:    " + ", ".join(f"{k}={v}" for k, v in g.items()))
    d = doc.get("devices")
    if d:
        add(f"devices:  {d.get('count')}x {d.get('device_kind')} "
            f"({d.get('platform')})")
    s = doc.get("sweep")
    if s:
        add(f"sweep:    backend={s.get('backend')} initial_k={s.get('initial_k')} "
            f"strict={s.get('strict_decrement')}")
    tu = doc.get("tuning")
    if tu:
        # schedule auto-tuner provenance (dgc_tpu.tune): which config
        # produced the engine schedule this run executed
        knobs = tu.get("knobs") or {}
        win = tu.get("win_total_pct")
        add(f"tuning:   source={tu.get('source')}"
            + (f" path={tu.get('path')}" if tu.get("path") else "")
            + (f" modeled_win={win}%" if win is not None else "")
            + ("" if tu.get("hash_match", True) else " [GRAPH-HASH MISMATCH]")
            + ("" if tu.get("backend_applies", True) else " [backend ignores it]"))
        if knobs:
            add(f"          knobs: "
                + ", ".join(f"{k}={'<ladder:%d rungs>' % len(v) if k == 'stages' else v}"
                            for k, v in sorted(knobs.items())))

    attempts = doc.get("attempts") or []
    if attempts:
        add("")
        add(f"attempts ({len(attempts)}):")
        add(f"  {'k':>6} {'status':<8} {'steps':>6} {'colors':>7}  trajectory (active/superstep)")
        for att in attempts:
            traj = att.get("trajectory") or {}
            active = traj.get("active") or []
            spark = sparkline(active, width)
            extra = ""
            if traj.get("truncated"):
                extra = " (truncated)"
            elif traj.get("first_step", 0) > 1 and active:
                extra = f" (resumed @s{traj['first_step']})"
            colors = att.get("colors_used")
            add(f"  {att.get('k', '?'):>6} {att.get('status', '?'):<8} "
                f"{att.get('supersteps', '?'):>6} "
                f"{colors if colors is not None else '-':>7}  {spark}{extra}")
            if traj.get("fail") and any(traj["fail"]):
                add(f"{'':>38}conflict superstep(s): "
                    f"{[i + traj.get('first_step', 0) for i, f in enumerate(traj['fail']) if f]}")
            gc = [c for c in (traj.get("gather_calls") or []) if c >= 0]
            if gc:
                # the segmented-plan schedule metric (obs.kernel col 3):
                # neighbor-gather calls the kernel issued per superstep
                add(f"{'':>38}gather calls/superstep: "
                    f"mean {sum(gc) / len(gc):.1f} max {max(gc)}")
            mu = [c for c in (traj.get("max_unconf") or []) if c >= 0]
            if mu:
                # the capture-validity bar (obs.kernel col 4): max
                # unconfirmed neighbors any gathered row saw
                add(f"{'':>38}max unconfirmed nbrs: "
                    f"peak {max(mu)} final {mu[-1]}")
            su = [c for c in (traj.get("step_us") or []) if c >= 0]
            if su:
                # the in-kernel timing column (obs.kernel col 5):
                # per-superstep wall µs measured inside the while loop
                add(f"{'':>38}device time/superstep: "
                    f"mean {sum(su) / len(su):.0f} µs max {max(su)} µs "
                    f"(in-kernel total {sum(su) / 1e3:.1f} ms)")

    sv = doc.get("serve")
    if sv:
        add("")
        cfg = sv.get("config") or {}
        add(f"serve:    batch_max={cfg.get('batch_max')} "
            f"window_ms={cfg.get('window_ms')} "
            f"queue_depth={cfg.get('queue_depth')}"
            + (f" mode={cfg.get('mode')}" if cfg.get("mode") else "")
            + (f" mesh_devices={cfg.get('mesh_devices')}"
               if cfg.get("mesh_devices") else ""))
        summ0 = sv.get("summary") or {}
        if summ0.get("device_occupancy"):
            # multi-device serve tier: mean live-lane occupancy per mesh
            # device over the whole run (the serve_slice events carry
            # the per-dispatch series)
            occ_d = summ0["device_occupancy"]
            add(f"  mesh: {summ0.get('mesh_devices')} device(s), mean "
                f"per-device occupancy "
                + " ".join(f"{x:.2f}" for x in occ_d))
        warm = sv.get("warmup")
        if warm:
            add(f"  warmup: {warm.get('kernels')} kernel(s) over "
                f"{warm.get('classes')} class(es) in "
                f"{warm.get('seconds')}s (off the serve clock)")
        slices = sv.get("slices") or []
        if slices:
            # lane recycling (continuous mode): pool occupancy over time
            # — the live-lanes/pool-width ratio per sliced dispatch, plus
            # how many sweeps recycled through the pool
            occ = [s.get("occupancy", 0) for s in slices]
            add(f"  slices: {len(slices)} "
                f"(mean lane occupancy {sum(occ) / len(occ):.2f}, "
                f"{sv.get('recycles', 0)} lane recycle(s), "
                f"{sum(1 for s in slices if s.get('compile_cache') == 'miss')}"
                f" compile miss(es))")
            add(f"  occupancy/slice: {sparkline(occ, width)}")
            # staged frontier ladder (CARRY_RUNG/CARRY_NC telemetry):
            # the rung the pool executed at over time, and how full the
            # compacted gather slots ran
            rungs = [s["stage_max"] for s in slices
                     if s.get("stage_max") is not None]
            if rungs and max(rungs) > 0:
                so = [s["stage_occupancy"] for s in slices
                      if s.get("stage_occupancy") is not None]
                fr = [s["frontier"] for s in slices
                      if s.get("frontier") is not None]
                add(f"  stages: deepest rung {max(rungs)} "
                    f"(mean stage occupancy {sum(so) / len(so):.2f}, "
                    f"peak frontier {max(fr)})")
                add(f"  rung/slice: {sparkline(rungs, width)}")
            h2d = sum(s.get("h2d_bytes", 0) for s in slices)
            d2h = sum(s.get("d2h_bytes", 0) for s in slices)
            if h2d or d2h:
                add(f"  transfers: {h2d / 1e6:.1f} MB host→device, "
                    f"{d2h / 1e6:.1f} MB device→host "
                    f"({(h2d + d2h) / len(slices) / 1e3:.1f} KB/slice)")
            ss = [s["sstep_ms"] for s in slices
                  if s.get("sstep_ms") is not None]
            ov = [s["overhead_ms"] for s in slices
                  if s.get("overhead_ms") is not None]
            if ss:
                # in-kernel timing split (slice kernel timing slots):
                # superstep compute vs dispatch overhead per slice
                add(f"  timing/slice: superstep {sum(ss) / len(ss):.1f} ms, "
                    f"dispatch overhead {sum(ov) / len(ov):.1f} ms "
                    f"(mean over {len(ss)} timed slice(s))")
        for rc_ in sv.get("recalibrations") or []:
            add(f"  slice recalibrated: {rc_.get('shape_class')} "
                f"{rc_.get('from_steps')} -> {rc_.get('to_steps')} steps "
                f"(measured overhead {rc_.get('overhead_ms')} ms, "
                f"superstep {rc_.get('sstep_ms')} ms)")
        batches = sv.get("batches") or []
        if batches:
            occ = [b.get("occupancy", 0) for b in batches]
            waste = [b.get("padding_waste", 0) for b in batches]
            misses = sum(1 for b in batches
                         if b.get("compile_cache") == "miss")
            straggle = [b["straggler_waste"] for b in batches
                        if b.get("straggler_waste") is not None]
            add(f"  batches: {len(batches)} "
                f"(mean occupancy {sum(occ) / len(occ):.2f}, mean padding "
                f"waste {sum(waste) / len(waste):.2f}, "
                + (f"mean straggler waste "
                   f"{sum(straggle) / len(straggle):.2f}, " if straggle
                   else "")
                + f"{misses} compile miss(es))")
            add(f"  occupancy/batch: {sparkline(occ, width)}")
        reqs = sv.get("requests") or []
        if reqs:
            lat = sorted(r.get("service_ms", 0) for r in reqs)
            q = sorted(r.get("queue_ms", 0) for r in reqs)
            p = lambda xs, f: xs[min(len(xs) - 1, int(f * len(xs)))]
            add(f"  requests: {len(reqs)} "
                f"(service p50 {p(lat, .5):.1f} ms, p95 {p(lat, .95):.1f} "
                f"ms, p99 {p(lat, .99):.1f} ms; "
                f"queue p95 {p(q, .95):.1f} ms)")
        summ = sv.get("summary")
        if summ and summ.get("latency_ms"):
            # the SLO layer's per-shape-class histogram summary
            # (serve_summary.latency_ms, bucket-interpolated quantiles)
            for cls in sorted(summ["latency_ms"]):
                lm = summ["latency_ms"][cls]
                add(f"  slo {cls}: p50 {lm.get('p50')} ms, "
                    f"p95 {lm.get('p95')} ms, p99 {lm.get('p99')} ms "
                    f"({lm.get('count')} request(s))")
        if summ:
            gps = summ.get("graphs_per_s")
            add(f"  summary: {summ.get('completed')}/{summ.get('requests')} "
                f"ok, {summ.get('failed')} failed, "
                f"{summ.get('rejected', 0)} shed"
                + (f", {gps} graphs/s" if gps is not None else "")
                + (f", {summ['mesh_degrades']} mesh degrade(s) "
                   f"({summ.get('lanes_evacuated', 0)} lane(s) evacuated)"
                   if summ.get("mesh_degrades") else ""))
        spec = sv.get("speculation")
        if spec or (summ and summ.get("spec_seated") is not None):
            # speculative minimal-k plane (the slot appears only when
            # --speculate-k armed it); summary totals win over the
            # per-event aggregates when both are present
            seated = (summ or {}).get("spec_seated",
                                      (spec or {}).get("seated", 0))
            wins = (summ or {}).get("spec_wins",
                                    (spec or {}).get("wins", 0))
            cancelled = (summ or {}).get(
                "spec_cancelled",
                sum((spec or {}).get("cancelled", {}).values()))
            wasted = (summ or {}).get(
                "spec_wasted_steps", (spec or {}).get("wasted_steps", 0))
            add(f"  speculation: {seated} seated, {wins} win(s), "
                f"{cancelled} cancelled "
                f"({wasted} superstep(s) wasted"
                + (f", {summ['spec_preempted']} preempted"
                   if summ and summ.get("spec_preempted") else "")
                + ")")
        if summ and summ.get("cache_hits") is not None:
            # content-addressed result cache totals (the slot appears
            # only when the cache was armed)
            add(f"  result cache: {summ['cache_hits']} hit(s), "
                f"{summ.get('cache_coalesced', 0)} coalesced, "
                f"{summ.get('cache_misses', 0)} miss(es), "
                f"{summ.get('cache_stores', 0)} store(s), "
                f"{summ.get('cache_entries', 0)} resident")
        rebuilds = sv.get("rebuilds") or []
        if rebuilds:
            # fault-plane recoveries: pool teardown/rebuild + poison
            # quarantines (the crash-safe serve tier's lane_rebuild)
            quarantined = sum(r.get("quarantined", 0) for r in rebuilds)
            hangs = sum(1 for r in rebuilds if r.get("reason") == "hang")
            add(f"  rebuilds: {len(rebuilds)} ({hangs} watchdog hang(s), "
                f"{quarantined} request(s) quarantined)")
        mesh_ev = sv.get("mesh_events") or []
        if mesh_ev:
            # failure-domain plane: every mesh reshape in order —
            # degrade (device loss -> survivor sub-mesh) and restore
            walk = " -> ".join(
                f"{e.get('devices_before')}→{e.get('devices_after')}"
                f"{'' if e.get('event') == 'mesh_restore' else ' (lost dev ' + str(e.get('lost_device')) + ')'}"
                for e in mesh_ev)
            evacuated = sum(e.get("reseated", 0) for e in mesh_ev)
            degrades = sum(1 for e in mesh_ev
                           if e.get("event") == "mesh_degrade")
            add(f"  mesh resilience: {degrades} degrade(s), "
                f"{len(mesh_ev) - degrades} restore(s), "
                f"{evacuated} lane(s) evacuated [{walk}]")
        hl = sv.get("health")
        if hl is not None and (not hl.get("ready") or hl.get("degraded")):
            add(f"  health: ready={hl.get('ready')} "
                f"degraded={hl.get('degraded')} "
                f"backend={hl.get('backend')} rung={hl.get('rung')}")
        if hl is not None and hl.get("mesh") is not None:
            m = hl["mesh"]
            add(f"  mesh health: {m.get('devices_surviving')}/"
                f"{m.get('devices_total')} device(s) surviving"
                + (", DEGRADED" if m.get("degraded") else ""))

    nf = doc.get("netfront")
    if nf:
        # network front door (serve.netfront): per-tenant admission
        # breakdown + the graceful-drain record
        add("")
        tenants = nf.get("tenants") or {}
        total_adm = sum(t.get("admitted", 0) for t in tenants.values())
        total_rej = sum(sum((t.get("rejected") or {}).values())
                        for t in tenants.values())
        add(f"netfront: {total_adm} admitted, {total_rej} rejected "
            f"across {len(tenants)} tenant(s)")
        for name in sorted(tenants):
            t = tenants[name]
            rej = t.get("rejected") or {}
            rej_s = ", ".join(f"{r} {n}" for r, n in sorted(rej.items()))
            add(f"  tenant {name}: {t.get('admitted', 0)} admitted"
                + (f", rejected: {rej_s}" if rej else ""))
        dr = nf.get("drain")
        if dr:
            add(f"  drain: {dr.get('in_flight')} in flight + "
                f"{dr.get('queued')} queued at drain, "
                f"{dr.get('completed')} completed / "
                f"{dr.get('failed')} failed in {dr.get('wall_s')}s")
        rec = nf.get("recover")
        if rec:
            # journal recovery (crash-safe serve tier): what a restart
            # pulled back out of the durable ticket journal
            add(f"  journal recovery: {rec.get('restored', 0)} restored, "
                f"{rec.get('replayed', 0)} replayed, "
                f"{rec.get('failed', 0)} failed "
                f"({rec.get('records', 0)} record(s), high water "
                f"{rec.get('high_water')}, {rec.get('wall_s')}s)")
        cache = nf.get("cache")
        if cache:
            # net_cache per-request outcomes (manifest aggregates the
            # stream to action counts; hit/coalesced are the dedup wins,
            # promote is a follower recomputing after leader loss)
            order = ("hit", "coalesced", "miss", "store", "promote")
            parts = [f"{cache[a]} {a}" for a in order if cache.get(a)]
            parts += [f"{n} {a}" for a, n in sorted(cache.items())
                      if a not in order]
            add("  result cache: " + ", ".join(parts))

    ph = doc.get("phases") or {}
    totals = ph.get("totals") or {}
    if totals:
        add("")
        add("phase breakdown (s):")
        span = sum(totals.values()) or 1.0
        for name in sorted(totals, key=totals.get, reverse=True):
            if name == "sweep_total":  # umbrella — overlaps compile/device
                continue
            v = totals[name]
            add(f"  {name:<18} {v:>9.4f}  {'#' * max(1, int(v / span * 30))}")
        if "sweep_total" in totals:
            add(f"  {'(sweep_total)':<18} {totals['sweep_total']:>9.4f}")

    for mem in doc.get("device_memory") or []:
        if mem.get("bytes_in_use") is not None:
            add(f"memory:   {mem.get('device')}: "
                f"{mem['bytes_in_use'] / 1e6:.1f} MB in use"
                + (f" (peak {mem['peak_bytes_in_use'] / 1e6:.1f} MB)"
                   if mem.get("peak_bytes_in_use") is not None else ""))

    res = doc.get("resilience") or {}
    if any(res.get(k) for k in ("faults", "retries", "fallbacks", "resumes")):
        add(f"resilience: {len(res.get('faults') or [])} fault(s) injected, "
            f"{len(res.get('retries') or [])} retr(ies), "
            f"{len(res.get('fallbacks') or [])} fallback(s), "
            f"{len(res.get('resumes') or [])} resume(s)")
        for fb in res.get("fallbacks") or []:
            add(f"fallback: {fb.get('from_backend')} -> {fb.get('to_backend')} "
                f"({fb.get('error_class')})")

    # diagnose-after-the-fact pointers (PR 11): where the dumps and
    # profile artifacts landed, and what the ledger said
    for pw in doc.get("profiles") or []:
        add(f"profile:  [{pw.get('trigger')}] {pw.get('seconds')}s window "
            f"-> {pw.get('xplane') or pw.get('logdir') + ' (no artifact)'}")
    xc = doc.get("timing_crosscheck")
    if xc:
        add(f"xcheck:   timing column {xc.get('in_kernel_ms')} ms vs "
            f"xplane {xc.get('xplane_ms')} ms self-time "
            f"(coverage {xc.get('coverage')}) -> "
            f"{str(xc.get('verdict')).upper()}")
    for fr in doc.get("flightrec") or []:
        add(f"flightrec: {fr.get('records')} event(s) "
            f"({fr.get('reason')}"
            + (f", {len(fr.get('open_spans'))} span(s) in flight"
               if fr.get("open_spans") else "")
            + f") -> {fr.get('path')}")
    for pv in doc.get("perf") or []:
        if pv.get("samples"):
            word = "REGRESSION" if pv.get("regression") else "ok"
            add(f"perf:     {pv.get('metric')} = {pv.get('value')} "
                f"{pv.get('unit') or ''} vs median "
                f"{pv.get('baseline_median')} over {pv.get('samples')} "
                f"run(s): {pv.get('delta_pct'):+.1f}% -> {word}")
        else:
            add(f"perf:     {pv.get('metric')} = {pv.get('value')} "
                f"{pv.get('unit') or ''} (baseline seeded)")

    for ab in doc.get("aborts") or []:
        if ab.get("event") == "structured_abort":
            add(f"ABORT:    structured (rc {ab.get('rc')}): {ab.get('reason')}")
        else:
            add(f"ABORT:    {ab.get('what')}: {ab.get('diag')}")

    pr = doc.get("post_reduce")
    if pr:
        add(f"reduce:   {pr.get('from_colors')} -> {pr.get('to_colors')} colors "
            f"in {pr.get('time_s')}s")

    res = doc.get("result")
    add("")
    if res and res.get("event") == "sweep_done":
        add(f"RESULT:   {res.get('minimal_colors')} colors, "
            f"{res.get('attempts')} attempts, {res.get('supersteps')} supersteps, "
            f"{res.get('wall_time_s')}s wall")
    elif res:
        add(f"RESULT:   FAILED (initial_k={res.get('initial_k')})")
    elif sv and sv.get("summary"):
        summ = sv["summary"]
        add(f"RESULT:   serve loop done — "
            f"{summ.get('completed')}/{summ.get('requests')} requests ok"
            + (f", {summ.get('graphs_per_s')} graphs/s"
               if summ.get("graphs_per_s") is not None else ""))
    else:
        add("RESULT:   (run did not complete)")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="run manifest JSON or JSONL run log")
    p.add_argument("--width", type=int, default=48,
                   help="sparkline width (supersteps are down-sampled)")
    args = p.parse_args(argv)
    try:
        doc = _load(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot load {args.path}: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(render(doc, width=args.width))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Export a run log's span events to Perfetto-loadable chrome-trace JSON.

Reads a JSONL run log (``--log-json``) containing ``span`` events
(``dgc_tpu.obs.trace``) and writes the Chrome Trace Event Format JSON
that https://ui.perfetto.dev (and chrome://tracing) load directly: one
process track per trace id — so one request's whole life (queue wait,
worker service, batched sweep, lane seating, every recycle boundary) is
one clickable trace, with the scheduler's ``slice``/``batch`` spans on
their own ``sched`` track aligned on the same clock.

Begin/end pairs become complete ("X") events; a span whose end never
arrived (a crashed or still-running producer) is emitted with zero
duration and ``args.unclosed = true`` so it is visible, not dropped.
Torn trailing lines (a live log mid-write) are tolerated.

Multi-log merge (cross-incarnation traces): given SEVERAL run logs —
e.g. ``server_0.jsonl .. server_N.jsonl`` from a kill-resume soak —
spans are paired within each file (span ids like ``s3`` restart per
process and would collide across files), but process tracks are keyed
by TRACE id across all files: a journal-replayed ticket that resumed
its original trace in a later incarnation lands on the SAME Perfetto
track as its first attempt, one thread lane per incarnation
(``thread_name`` = the source file). That is the cross-boundary
propagation proof: one trace id, one track, N incarnations.

Usage:
    python tools/export_trace.py RUN.jsonl -o trace.json
    python tools/export_trace.py RUN.jsonl --trace req-7   # one request
    python tools/export_trace.py server_*.jsonl -o merged.json  # merge
"""

from __future__ import annotations

import argparse
import json
import sys


def read_spans(path: str) -> list[dict]:
    """All span events in one JSONL log, in file order; the final line
    may be torn (no newline yet) and is ignored if unparseable."""
    spans = []
    with open(path) as fh:
        raw = fh.read()
    lines = raw.split("\n")
    torn_tail = not raw.endswith("\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if torn_tail and i == len(lines) - 1:
                continue
            raise ValueError(
                f"{path}:{i + 1}: unparseable JSON line") from e
        if isinstance(rec, dict) and rec.get("event") == "span":
            spans.append(rec)
    return spans


def to_chrome_trace(spans: list[dict], trace_filter: str | None = None) -> dict:
    """Pair B/E records into complete events; one pid per trace id.

    Within a trace every span goes on tid 1 — request spans are properly
    nested by construction (request ⊃ queue/serve ⊃ sweep ⊃ lane), which
    is exactly the containment Perfetto stacks slices by."""
    open_spans: dict = {}    # (trace, span) -> begin record
    events: list = []
    pids: dict = {}          # trace id -> pid

    def pid_for(trace: str) -> int:
        if trace not in pids:
            pids[trace] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[trace], "tid": 0,
                           "args": {"name": trace}})
        return pids[trace]

    for rec in spans:
        trace = rec.get("trace")
        if trace_filter is not None and trace != trace_filter:
            continue
        key = (trace, rec.get("span"))
        if rec.get("ph") == "B":
            open_spans[key] = rec
        elif rec.get("ph") == "E":
            begin = open_spans.pop(key, None)
            if begin is None:
                continue   # end without begin: validator territory
            args = dict(begin.get("attrs") or {})
            args.update(rec.get("attrs") or {})
            args["span"] = rec.get("span")
            if begin.get("parent"):
                args["parent"] = begin["parent"]
            events.append({
                "ph": "X", "name": begin.get("name", "?"), "cat": "dgc",
                "pid": pid_for(trace), "tid": 1,
                "ts": begin.get("ts_us", 0),
                "dur": max(0, rec.get("ts_us", 0) - begin.get("ts_us", 0)),
                "args": args,
            })
    for (trace, span_id), begin in open_spans.items():
        args = dict(begin.get("attrs") or {})
        args.update(span=span_id, unclosed=True)
        events.append({
            "ph": "X", "name": begin.get("name", "?"), "cat": "dgc",
            "pid": pid_for(trace), "tid": 1,
            "ts": begin.get("ts_us", 0), "dur": 0, "args": args,
        })
    events.sort(key=lambda e: (e["pid"], e.get("ts", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_chrome_traces(labeled: list,
                        trace_filter: str | None = None) -> dict:
    """Merge N runs' spans into one chrome trace. ``labeled`` is
    ``[(label, spans), ...]`` — one entry per run-log file, in
    incarnation order.

    B/E pairing is PER FILE (every process restarts its ``s<N>`` span
    id counter, so ``(trace, span)`` keys collide across files), but
    the process track is per TRACE id across ALL files — the merged
    view shows a crash-resumed request as one track whose thread lanes
    are its incarnations."""
    events: list = []
    pids: dict = {}          # trace id -> pid (shared across files)
    named_tids: set = set()  # (pid, tid) with thread_name emitted

    def pid_for(trace: str) -> int:
        if trace not in pids:
            pids[trace] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[trace], "tid": 0,
                           "args": {"name": trace}})
        return pids[trace]

    def lane(trace: str, tid: int, label: str) -> int:
        pid = pid_for(trace)
        if (pid, tid) not in named_tids:
            named_tids.add((pid, tid))
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": label}})
        return pid

    for tid, (label, spans) in enumerate(labeled, 1):
        open_spans: dict = {}
        for rec in spans:
            trace = rec.get("trace")
            if trace_filter is not None and trace != trace_filter:
                continue
            key = (trace, rec.get("span"))
            if rec.get("ph") == "B":
                open_spans[key] = rec
            elif rec.get("ph") == "E":
                begin = open_spans.pop(key, None)
                if begin is None:
                    continue
                args = dict(begin.get("attrs") or {})
                args.update(rec.get("attrs") or {})
                args["span"] = rec.get("span")
                args["source"] = label
                if begin.get("parent"):
                    args["parent"] = begin["parent"]
                events.append({
                    "ph": "X", "name": begin.get("name", "?"),
                    "cat": "dgc", "pid": lane(trace, tid, label),
                    "tid": tid, "ts": begin.get("ts_us", 0),
                    "dur": max(0, rec.get("ts_us", 0)
                               - begin.get("ts_us", 0)),
                    "args": args,
                })
        for (trace, span_id), begin in open_spans.items():
            args = dict(begin.get("attrs") or {})
            args.update(span=span_id, unclosed=True, source=label)
            events.append({
                "ph": "X", "name": begin.get("name", "?"), "cat": "dgc",
                "pid": lane(trace, tid, label), "tid": tid,
                "ts": begin.get("ts_us", 0), "dur": 0, "args": args,
            })
    events.sort(key=lambda e: (e["pid"], e["tid"], e.get("ts", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", metavar="PATH",
                   help="JSONL run log(s) with span events; several "
                        "paths (incarnation order) are merged by trace "
                        "id, one thread lane per file")
    p.add_argument("-o", "--out", default=None,
                   help="output trace JSON (default: stdout)")
    p.add_argument("--trace", default=None, metavar="TRACE_ID",
                   help="export only this trace (e.g. req-7)")
    args = p.parse_args(argv)
    labeled = []
    try:
        for path in args.paths:
            labeled.append((path, read_spans(path)))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not any(spans for _, spans in labeled):
        print(f"{', '.join(args.paths)}: no span events (tracing off, "
              f"or not a serve log?)", file=sys.stderr)
        return 1
    if len(labeled) == 1:
        doc = to_chrome_trace(labeled[0][1], trace_filter=args.trace)
    else:
        doc = merge_chrome_traces(labeled, trace_filter=args.trace)
    if not doc["traceEvents"]:
        print(f"--trace {args.trace}: no matching spans", file=sys.stderr)
        return 1
    out = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out + "\n")
        n = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        print(f"{args.out}: {n} span(s), "
              f"{len({e['pid'] for e in doc['traceEvents']})} track(s)")
    else:
        print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

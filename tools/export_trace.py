#!/usr/bin/env python
"""Export a run log's span events to Perfetto-loadable chrome-trace JSON.

Reads a JSONL run log (``--log-json``) containing ``span`` events
(``dgc_tpu.obs.trace``) and writes the Chrome Trace Event Format JSON
that https://ui.perfetto.dev (and chrome://tracing) load directly: one
process track per trace id — so one request's whole life (queue wait,
worker service, batched sweep, lane seating, every recycle boundary) is
one clickable trace, with the scheduler's ``slice``/``batch`` spans on
their own ``sched`` track aligned on the same clock.

Begin/end pairs become complete ("X") events; a span whose end never
arrived (a crashed or still-running producer) is emitted with zero
duration and ``args.unclosed = true`` so it is visible, not dropped.
Torn trailing lines (a live log mid-write) are tolerated.

Usage:
    python tools/export_trace.py RUN.jsonl -o trace.json
    python tools/export_trace.py RUN.jsonl --trace req-7   # one request
"""

from __future__ import annotations

import argparse
import json
import sys


def read_spans(path: str) -> list[dict]:
    """All span events in one JSONL log, in file order; the final line
    may be torn (no newline yet) and is ignored if unparseable."""
    spans = []
    with open(path) as fh:
        raw = fh.read()
    lines = raw.split("\n")
    torn_tail = not raw.endswith("\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if torn_tail and i == len(lines) - 1:
                continue
            raise ValueError(
                f"{path}:{i + 1}: unparseable JSON line") from e
        if isinstance(rec, dict) and rec.get("event") == "span":
            spans.append(rec)
    return spans


def to_chrome_trace(spans: list[dict], trace_filter: str | None = None) -> dict:
    """Pair B/E records into complete events; one pid per trace id.

    Within a trace every span goes on tid 1 — request spans are properly
    nested by construction (request ⊃ queue/serve ⊃ sweep ⊃ lane), which
    is exactly the containment Perfetto stacks slices by."""
    open_spans: dict = {}    # (trace, span) -> begin record
    events: list = []
    pids: dict = {}          # trace id -> pid

    def pid_for(trace: str) -> int:
        if trace not in pids:
            pids[trace] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[trace], "tid": 0,
                           "args": {"name": trace}})
        return pids[trace]

    for rec in spans:
        trace = rec.get("trace")
        if trace_filter is not None and trace != trace_filter:
            continue
        key = (trace, rec.get("span"))
        if rec.get("ph") == "B":
            open_spans[key] = rec
        elif rec.get("ph") == "E":
            begin = open_spans.pop(key, None)
            if begin is None:
                continue   # end without begin: validator territory
            args = dict(begin.get("attrs") or {})
            args.update(rec.get("attrs") or {})
            args["span"] = rec.get("span")
            if begin.get("parent"):
                args["parent"] = begin["parent"]
            events.append({
                "ph": "X", "name": begin.get("name", "?"), "cat": "dgc",
                "pid": pid_for(trace), "tid": 1,
                "ts": begin.get("ts_us", 0),
                "dur": max(0, rec.get("ts_us", 0) - begin.get("ts_us", 0)),
                "args": args,
            })
    for (trace, span_id), begin in open_spans.items():
        args = dict(begin.get("attrs") or {})
        args.update(span=span_id, unclosed=True)
        events.append({
            "ph": "X", "name": begin.get("name", "?"), "cat": "dgc",
            "pid": pid_for(trace), "tid": 1,
            "ts": begin.get("ts_us", 0), "dur": 0, "args": args,
        })
    events.sort(key=lambda e: (e["pid"], e.get("ts", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="JSONL run log with span events")
    p.add_argument("-o", "--out", default=None,
                   help="output trace JSON (default: stdout)")
    p.add_argument("--trace", default=None, metavar="TRACE_ID",
                   help="export only this trace (e.g. req-7)")
    args = p.parse_args(argv)
    try:
        spans = read_spans(args.path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"{args.path}: no span events (tracing off, or not a serve "
              f"log?)", file=sys.stderr)
        return 1
    doc = to_chrome_trace(spans, trace_filter=args.trace)
    if not doc["traceEvents"]:
        print(f"--trace {args.trace}: no matching spans", file=sys.stderr)
        return 1
    out = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out + "\n")
        n = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        print(f"{args.out}: {n} span(s), "
              f"{len({e['pid'] for e in doc['traceEvents']})} track(s)")
    else:
        print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Perf-history ledger: append-only JSONL of bench results + regression
verdicts.

Every CPU-measured serve win is "a prediction, not a result" (ROADMAP
item 5) partly because nothing persists performance over time — BENCH_*
.json files are loose snapshots nobody compares. This ledger makes the
trajectory a data structure: ``bench.py --perf-db PATH`` (and this CLI)
append one entry per measurement, keyed by

    (metric, graph-shape hash, config hash, host, platform, backend)

and every append is checked against the **median of the key's prior
entries**: a value worse than ``median × (1 + threshold)`` (direction-
aware — seconds want lower, graphs/s want higher) is a regression, and
the check exits nonzero exactly like ``tools/slo_check.py`` — a perf
regression fails the run, it does not just lower a number in a file.
When the axon tunnel returns, the evidence battery's rows land here and
the next round can ask "faster or slower than last round?" of a store
instead of a human.

Entry schema (one JSON object per line)::

    {"key": {"metric", "shape", "config", "host", "platform", "backend"},
     "value", "unit", "better": "lower"|"higher",
     "verdict": {...perf_regression fields...}, "record": {...}}

``config`` hashes the measurement-relevant knobs of the bench record
(mode/slice/tuning/compile flags) so a tuned run never compares against
an untuned baseline; ``record`` keeps the full bench JSON line for
forensics.

CLI:
  python tools/perf_db.py add --db PERF_DB.jsonl [--record FILE|-]
      [--threshold 0.10] [--dry-run]       # exit 1 on regression
  python tools/perf_db.py report --db PERF_DB.jsonl [--metric SUBSTR]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_THRESHOLD = 0.10

# bench-record fields that change what the number MEANS (two entries are
# comparable history only when all of these match); the metric string
# already encodes nodes/avg-degree/generator/backend/batch
_CONFIG_FIELDS = ("metric", "unit", "backend", "platform", "serve_mode",
                  "slice_steps", "tuned_config", "shape_class",
                  "include_compile")

# units where smaller is better; rates are better bigger
_LOWER_IS_BETTER_UNITS = ("s", "ms", "us", "bytes")


def config_hash(record: dict) -> str:
    """Stable hash of the measurement-relevant bench-record config."""
    cfg = {k: record.get(k) for k in _CONFIG_FIELDS}
    blob = json.dumps(cfg, sort_keys=True).encode()
    return "dgccfg-" + hashlib.sha256(blob).hexdigest()[:16]


def better_direction(record: dict) -> str:
    # an explicit record-level direction wins — unit alone is ambiguous
    # for "pct" (overhead wants lower, occupancy would want higher)
    if record.get("better") in ("lower", "higher"):
        return record["better"]
    unit = record.get("unit")
    return "lower" if unit in _LOWER_IS_BETTER_UNITS else "higher"


def entry_key(record: dict, *, host: str | None = None) -> dict:
    return {
        "metric": record.get("metric"),
        "shape": record.get("graph_shape_hash"),
        "config": config_hash(record),
        "host": host or socket.gethostname(),
        "platform": record.get("platform"),
        "backend": record.get("backend"),
    }


def load(path: str) -> list:
    """All parseable entries of a ledger (a torn trailing line — a run
    killed mid-append — is tolerated like every JSONL reader here)."""
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        raw = fh.read()
    lines = raw.split("\n")
    torn_tail = not raw.endswith("\n")
    out = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if torn_tail and i == len(lines) - 1:
                continue
            raise
    return out


def history_values(entries: list, key: dict) -> list:
    """Prior values of one key, in append order (None values — abort
    records — never enter the ledger, but skip defensively)."""
    return [e["value"] for e in entries
            if e.get("key") == key and e.get("value") is not None]


def _median(xs: list) -> float:
    ys = sorted(xs)
    n = len(ys)
    return ys[n // 2] if n % 2 else (ys[n // 2 - 1] + ys[n // 2]) / 2.0


def check(baseline: list, value: float, better: str,
          threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Regression verdict of ``value`` against the key's history
    (``perf_regression`` event fields, obs.schema). No history → no
    verdict to render, never a regression (the first entry seeds the
    baseline)."""
    if not baseline:
        return {"regression": False, "baseline_median": None,
                "delta_pct": None, "samples": 0, "better": better,
                "threshold_pct": round(threshold * 100, 2)}
    med = _median(baseline)
    # delta_pct > 0 always means WORSE, whichever way better points
    if better == "lower":
        delta = (value - med) / med if med else 0.0
    else:
        delta = (med - value) / med if med else 0.0
    return {"regression": delta > threshold,
            "baseline_median": round(med, 6),
            "delta_pct": round(delta * 100, 2),
            "samples": len(baseline), "better": better,
            "threshold_pct": round(threshold * 100, 2)}


def record_and_check(db_path: str, record: dict, *,
                     threshold: float = DEFAULT_THRESHOLD,
                     host: str | None = None, append: bool = True,
                     logger=None) -> dict:
    """Append one bench record to the ledger and return its verdict
    (appended WITH the verdict, so the ledger is self-describing).
    Records without a measured value (abort records) are not appended
    and get a no-verdict result. ``logger`` (optional) emits the
    ``perf_regression`` event into a run-log stream."""
    value = record.get("value")
    verdict = {"metric": record.get("metric"), "value": value,
               "unit": record.get("unit"), "db": db_path}
    if value is None:
        verdict.update(check([], 0.0, better_direction(record), threshold))
        verdict["regression"] = False
        return verdict
    key = entry_key(record, host=host)
    entries = load(db_path)
    baseline = history_values(entries, key)
    verdict.update(check(baseline, float(value), better_direction(record),
                         threshold))
    if append:
        entry = {"key": key, "value": value, "unit": record.get("unit"),
                 "better": verdict["better"],
                 "verdict": {k: verdict[k] for k in
                             ("regression", "baseline_median", "delta_pct",
                              "samples", "threshold_pct")},
                 "record": record}
        with open(db_path, "a") as fh:
            fh.write(json.dumps(entry) + "\n")
    if logger is not None:
        logger.event("perf_regression", metric=verdict["metric"],
                     value=value, regression=verdict["regression"],
                     baseline_median=verdict["baseline_median"],
                     delta_pct=verdict["delta_pct"],
                     samples=verdict["samples"], better=verdict["better"],
                     threshold_pct=verdict["threshold_pct"],
                     db=db_path, unit=record.get("unit"))
    return verdict


def render_verdict(verdict: dict) -> str:
    """One human line (bench prints it to stderr beside the JSON)."""
    if verdict.get("samples", 0) == 0:
        return (f"perf-db: {verdict.get('metric')} = "
                f"{verdict.get('value')} {verdict.get('unit') or ''} "
                f"(first entry for this key — baseline seeded)")
    word = "REGRESSION" if verdict["regression"] else "ok"
    return (f"perf-db: {verdict.get('metric')} = {verdict.get('value')} "
            f"{verdict.get('unit') or ''} vs median "
            f"{verdict['baseline_median']} over {verdict['samples']} "
            f"run(s): {verdict['delta_pct']:+.1f}% "
            f"({verdict['better']} is better) -> {word}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pa = sub.add_parser("add", help="append a bench record + check")
    pa.add_argument("--db", required=True, help="ledger JSONL path")
    pa.add_argument("--record", default="-",
                    help="bench JSON record file, or - for stdin")
    pa.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression threshold as a fraction "
                         f"(default {DEFAULT_THRESHOLD})")
    pa.add_argument("--host", default=None,
                    help="override the host key (default: hostname)")
    pa.add_argument("--dry-run", action="store_true",
                    help="check without appending")
    pr = sub.add_parser("report", help="render the ledger's history")
    pr.add_argument("--db", required=True)
    pr.add_argument("--metric", default=None,
                    help="substring filter on the metric name")
    args = p.parse_args(argv)

    if args.cmd == "report":
        try:
            entries = load(args.db)
        except (OSError, ValueError) as e:
            print(f"error: cannot load {args.db}: {e}", file=sys.stderr)
            return 2
        by_key: dict = {}
        for e in entries:
            k = json.dumps(e.get("key"), sort_keys=True)
            by_key.setdefault(k, []).append(e)
        for k in sorted(by_key):
            key = json.loads(k)
            if args.metric and args.metric not in (key.get("metric") or ""):
                continue
            vals = [e["value"] for e in by_key[k]]
            last = by_key[k][-1]
            v = last.get("verdict") or {}
            print(f"{key.get('metric')} [{key.get('platform')}/"
                  f"{key.get('host')} {key.get('config')}]: "
                  f"{len(vals)} run(s), median {_median(vals):.6g}, "
                  f"last {vals[-1]:.6g}"
                  + (f" ({v.get('delta_pct'):+.1f}%"
                     f"{' REGRESSION' if v.get('regression') else ''})"
                     if v.get("delta_pct") is not None else ""))
        return 0

    try:
        raw = (sys.stdin.read() if args.record == "-"
               else open(args.record).read())
        record = json.loads(raw.strip().splitlines()[-1])
        if not isinstance(record, dict):
            raise ValueError("record must be a JSON object")
    except (OSError, ValueError, IndexError) as e:
        print(f"error: cannot load record: {e}", file=sys.stderr)
        return 2
    try:
        verdict = record_and_check(args.db, record,
                                   threshold=args.threshold,
                                   host=args.host,
                                   append=not args.dry_run)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(render_verdict(verdict), file=sys.stderr)
    print(json.dumps(verdict))
    return 1 if verdict.get("regression") else 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Live ops console over a serving listener's telemetry surface.

``dgc_top`` polls one dgc-tpu listener (the serve CLI's ``--listen``
port, or a standalone ``--metrics-port`` scraper) and renders a
refreshing terminal view of the fleet telemetry plane:

- build identity + uptime + readiness (``/healthz``, ``dgc_build_info``)
- queue depth / in-flight / capacity, and the lane-mesh block when the
  lane axis is sharded: surviving devices and per-device health
- per-tenant admission state (``/healthz`` tenants) joined with the
  live usage rollups (``GET /admin/usage``): admitted / delivered /
  failed / in-flight, vertices·supersteps, device-ms
- SLO burn status: ``dgc_slo_burn_fired_total`` by objective, plus the
  timeseries ring depth when the sampler is armed
  (``GET /debug/timeseries``)

Pure stdlib, read-only (GETs only), and tolerant of missing routes — a
listener without the sampler or the meter just renders fewer panes.
``--once`` prints a single frame and exits (the CI smoke's mode);
otherwise the screen clears and redraws every ``--interval`` seconds
(the ``tools/tail_run.py`` convention).

Usage:
    python tools/dgc_top.py --url http://127.0.0.1:8080
    python tools/dgc_top.py --url http://127.0.0.1:8080 --once
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request

CLEAR = "\x1b[2J\x1b[H"   # clear + home (tools/tail_run.py convention)

_SERIES_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def fetch(url: str, timeout: float = 3.0) -> str | None:
    """GET ``url``; None on any failure (a pane, not a crash)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None


def parse_prom(text: str) -> list:
    """Prometheus text lines as ``(name, labels_dict, value)`` tuples;
    comments and malformed lines are skipped."""
    out: list = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw or ""))
        out.append((name, labels, value))
    return out


def _select(series: list, name: str) -> list:
    return [(labels, value) for n, labels, value in series if n == name]


def _fmt_count(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else f"{v:.2f}"


def render_frame(base_url: str) -> str:
    """One console frame from the listener's live surfaces."""
    lines: list = []
    now = time.strftime("%H:%M:%S")
    lines.append(f"dgc-top  {base_url}  {now}")

    health_raw = fetch(f"{base_url}/healthz")
    health = None
    if health_raw:
        try:
            health = json.loads(health_raw)
        except json.JSONDecodeError:
            health = None
    if health is None:
        lines.append("  [unreachable: /healthz]")
        return "\n".join(lines) + "\n"

    build = health.get("build") or {}
    ident = " ".join(f"{k}={build[k]}" for k in sorted(build))
    up = health.get("uptime_s")
    if up is not None:
        ident = f"{ident or 'build=?'}  up={up:.0f}s"
    if ident:
        lines.append(f"  {ident}")
    state = "READY" if health.get("ready") else "NOT-READY"
    if health.get("draining"):
        state += " DRAINING"
    if health.get("degraded"):
        state += " DEGRADED"
    lines.append(f"  {state}  queue={health.get('queue_depth', '?')}"
                 f"  in_flight={health.get('in_flight', '?')}"
                 f"  capacity={health.get('capacity', '?')}")

    mesh = health.get("mesh")
    if isinstance(mesh, dict):
        lines.append(
            f"  mesh: {mesh.get('devices_surviving', '?')}/"
            f"{mesh.get('devices_total', '?')} devices"
            f"  degrades={mesh.get('degrades', 0)}"
            f"  restores={mesh.get('restores', 0)}")
        states = mesh.get("devices")
        if isinstance(states, list):
            glyphs = "".join("#" if s == "healthy" else "x"
                             for s in states)
            lines.append(f"  devices: [{glyphs}]")

    rc = health.get("result_cache")
    if isinstance(rc, dict):
        # content-addressed result cache (the pane appears only when
        # the listener was started with --result-cache)
        lines.append(
            f"  cache: {rc.get('entries', 0)}/{rc.get('capacity', '?')}"
            f" entries  hits={rc.get('hits', 0)}"
            f"  coalesced={rc.get('coalesced', 0)}"
            f"  misses={rc.get('misses', 0)}"
            + ("  [disk]" if rc.get("disk") else ""))

    series = parse_prom(fetch(f"{base_url}/metrics") or "")
    spec_seated = _select(series, "dgc_serve_spec_seated_total")
    if spec_seated:
        # speculative minimal-k pane (appears only when --speculate-k
        # armed the engine and at least one attempt was seated)
        seated = sum(v for _, v in spec_seated)
        wins = sum(v for _, v in
                   _select(series, "dgc_serve_spec_wins_total"))
        cancelled = sum(v for _, v in
                        _select(series, "dgc_serve_spec_cancelled_total"))
        wasted = sum(v for _, v in _select(
            series, "dgc_serve_spec_wasted_supersteps_total"))
        lines.append(f"  speculation: seated={_fmt_count(seated)}"
                     f"  wins={_fmt_count(wins)}"
                     f"  cancelled={_fmt_count(cancelled)}"
                     f"  wasted_steps={_fmt_count(wasted)}")
    burns = _select(series, "dgc_slo_burn_fired_total")
    if burns:
        burned = ", ".join(
            f"{labels.get('objective', '?')}x{_fmt_count(v)}"
            for labels, v in sorted(burns,
                                    key=lambda lv: -lv[1]) if v > 0)
        lines.append(f"  SLO BURN: {burned or 'none'}")

    ts_raw = fetch(f"{base_url}/debug/timeseries")
    if ts_raw is not None:
        samples = [ln for ln in ts_raw.splitlines() if ln.strip()]
        lines.append(f"  timeseries: {len(samples)} sample(s) in ring")

    # per-tenant pane: admission state joined with live usage rollups
    tenants = health.get("tenants") or {}
    usage_rows: dict = {}
    usage_raw = fetch(f"{base_url}/admin/usage")
    if usage_raw:
        try:
            for row in json.loads(usage_raw).get("usage", []):
                usage_rows[row.get("tenant")] = row
        except json.JSONDecodeError:
            pass
    names = sorted(set(tenants) | set(usage_rows))
    if names:
        lines.append("")
        lines.append(f"  {'tenant':<14} {'infl':>5} {'adm':>6} "
                     f"{'done':>6} {'fail':>5} {'abrt':>5} {'cach':>5} "
                     f"{'v*steps':>10} {'dev_ms':>9}")
        for name in names:
            adm = tenants.get(name) or {}
            row = usage_rows.get(name) or {}
            lines.append(
                f"  {name:<14} "
                f"{adm.get('in_flight', row.get('in_flight', 0)):>5} "
                f"{row.get('admitted', 0):>6} "
                f"{row.get('delivered', 0):>6} "
                f"{row.get('failed', 0):>5} "
                f"{row.get('aborted', 0):>5} "
                f"{row.get('cached', 0):>5} "
                f"{row.get('vertex_supersteps', 0):>10} "
                f"{row.get('device_ms', 0.0):>9.1f}")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="http://127.0.0.1:9100",
                   help="listener base URL (default "
                        "http://127.0.0.1:9100)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (CI mode; no screen "
                        "clearing)")
    args = p.parse_args(argv)
    base = args.url.rstrip("/")
    if args.once:
        frame = render_frame(base)
        sys.stdout.write(frame)
        return 0 if "[unreachable" not in frame else 1
    try:
        while True:
            frame = render_frame(base)
            sys.stdout.write(CLEAR + frame)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        print()
        return 0


if __name__ == "__main__":
    raise SystemExit(main())

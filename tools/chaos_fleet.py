#!/usr/bin/env python
"""Chaos harness for the replicated serve fleet: seeded SIGKILL
schedules over replica subsets + kill-all cold restart + the brownout
tier contract, over one shared ``--journal-dir``.

The fleet-level analogue of ``tools/chaos_serve.py`` (which hammers ONE
listener). Three legs, one report:

**Leg 1 — replica-subset kill schedule.** A real fleet
(``dgc-tpu serve --listen --replicas N --journal-dir``) serves
concurrent clients while a watcher thread SIGKILLs seeded replica
subsets whenever the MERGED write-ahead journal (all namespaces'
``ticket_journal.jsonl``) crosses the next seeded record offset — kills
land mid-group-commit by construction. The fleet supervisor respawns
each casualty under a fresh incarnation; clients ride the shared
SO_REUSEPORT port through every kill window. Asserted: every acked
(202) ticket reaches a terminal 200, zero duplicate ticket ids
FLEET-WIDE (the replica-prefix contract), and every replayed request's
colors are byte-identical to the fault-free baseline.

**Leg 2 — kill-all + cold fleet restart.** Every replica AND the
supervisor are SIGKILLed at once; a brand-new fleet process starts over
the same ``--journal-dir``. The cold fleet's merge-scan
(``scan_fleet``) must fold every incarnation's namespace: all of leg
1's tickets still poll to the same colors, the merged scan holds no
duplicate ids, and per-tenant usage conservation (PR 16's checker)
holds over the namespace WAL list.

**Leg 3 — brownout tier contract (in-process, deterministic).** A
listener with a ``BrownoutController`` forced through its burn
evaluations must shed ONLY the low tiers: at level 1 a free-tier submit
gets a structured 503 + ``Retry-After`` while premium traffic is
admitted and served; when the burn clears, the shed tier is admitted
again, and the ``net_brownout``/``net_reject`` stream schema-validates.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_fleet.py --replicas 2 \\
        --kills 2 --clients 4 --requests-per-client 2 \\
        --report /tmp/chaos_fleet.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.chaos_serve import (_baseline_colors, _free_port, _http,  # noqa: E402
                               _request_doc)
from tools.validate_runlog import validate_file  # noqa: E402

CHAOS_FLEET_REPORT_VERSION = 1

_OUTCOMES = ("ok", "hang", "error", "mismatch")


# ---------------------------------------------------------------------------
# the fleet under test
# ---------------------------------------------------------------------------

class _Fleet:
    """One ``serve --replicas N`` supervisor process + its replicas."""

    def __init__(self, port: int, journal_dir: str, log_base: str, args):
        self.cmd = [sys.executable, "-m", "dgc_tpu.cli", "serve",
                    "--listen", str(port), "--replicas",
                    str(args.replicas), "--journal-dir", journal_dir,
                    "--log-json", log_base,
                    "--batch-max", str(args.batch_max),
                    "--queue-depth",
                    str(max(64, args.clients
                            * args.requests_per_client * 2)),
                    "--window-ms", "0",
                    "--dispatch-timeout", str(args.dispatch_timeout),
                    "--max-lane-aborts", str(args.max_lane_aborts)]
        if getattr(args, "result_cache", 0) > 0:
            # per-replica LRUs over ONE shared content-addressed store
            # (a journal-dir sibling, so it survives kill-all the same
            # way the WAL does) — the supervisor forwards both flags to
            # every replica incarnation
            self.cmd += ["--result-cache", str(args.result_cache),
                         "--result-cache-dir",
                         os.path.join(os.path.dirname(journal_dir),
                                      "result_cache")]
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            self.cmd, env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.port = port
        self.journal_dir = journal_dir

    def state(self) -> dict:
        """The supervisor's ``fleet_state.json`` (written atomically;
        {} while it does not exist yet)."""
        try:
            with open(os.path.join(self.journal_dir,
                                   "fleet_state.json")) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return {}

    def replica_pids(self) -> dict:
        return {name: c["pid"]
                for name, c in self.state().get("children", {}).items()}

    def wait_ready(self, deadline_s: float = 180.0) -> None:
        t_end = time.perf_counter() + deadline_s
        while time.perf_counter() < t_end:
            if self.proc.poll() is not None:
                raise RuntimeError(f"fleet exited rc {self.proc.returncode}"
                                   f" before ready")
            if len(self.replica_pids()) > 0:
                try:
                    st, _doc = _http("GET", self.port, "/healthz",
                                     retries=1, deadline_s=5.0)
                    if st == 200:
                        return
                except RuntimeError:
                    pass
            time.sleep(0.1)
        raise RuntimeError("fleet never became ready")

    def kill_replicas(self, names) -> int:
        """SIGKILL the named replicas' CURRENT incarnations; returns
        how many signals landed."""
        landed = 0
        for name, pid in self.replica_pids().items():
            if name in names:
                try:
                    os.kill(pid, signal.SIGKILL)
                    landed += 1
                except OSError:
                    pass
        return landed

    def kill_all(self) -> None:
        """Kill-all: every replica AND the supervisor, no drain."""
        pids = list(self.replica_pids().values())
        self.proc.kill()
        self.proc.wait(timeout=30)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def _wal_records(journal_dir: str) -> int:
    """Merged WAL record count across every namespace — the kill
    clock."""
    total = 0
    try:
        names = os.listdir(journal_dir)
    except OSError:
        return 0
    for name in names:
        path = os.path.join(journal_dir, name, "ticket_journal.jsonl")
        try:
            with open(path, "rb") as fh:
                total += fh.read().count(b"\n")
        except OSError:
            continue
    return total


# ---------------------------------------------------------------------------
# legs 1+2: subset kills, then kill-all cold restart
# ---------------------------------------------------------------------------

def _drive_clients(args, reqs, port, tickets, ticket_of, results, errors):
    """Concurrent client threads: submit, then poll own tickets to
    terminal results, riding _http's reconnect loop through kills."""
    acct = threading.Lock()

    def client(reqs_slice):
        mine = []
        for doc in reqs_slice:
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                try:
                    st, body = _http("POST", port, "/v1/color", doc,
                                     retries=8, deadline_s=30.0)
                except RuntimeError:
                    continue   # fleet mid-respawn
                if st == 202:
                    with acct:
                        tickets.append(body["ticket"])
                        ticket_of[body["ticket"]] = doc
                    mine.append(body["ticket"])
                    break
                if st in (429, 503):
                    time.sleep(0.05)
                    continue
                with acct:
                    errors.append(f"submit HTTP {st}: {body}")
                break
        for ticket in mine:
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                try:
                    st, body = _http(
                        "GET", port, f"/v1/result/{ticket}?colors=1",
                        retries=8, deadline_s=30.0)
                except RuntimeError:
                    continue
                if st == 200:
                    with acct:
                        results[ticket] = body
                    break
                if st == 202:
                    time.sleep(0.02)
                    continue
                with acct:
                    if st == 404:
                        errors.append(f"acked ticket {ticket} LOST (404)")
                        results[ticket] = {"status": "lost"}
                    else:
                        errors.append(f"poll {ticket} HTTP {st}")
                        results[ticket] = {"status": f"http {st}"}
                break
            else:
                with acct:
                    errors.append(f"poll deadline for {ticket}")

    per = max(1, args.requests_per_client)
    slices = [reqs[i:i + per] for i in range(0, len(reqs), per)]
    threads = [threading.Thread(target=client, args=(s,), daemon=True)
               for s in slices]
    for t in threads:
        t.start()
    deadline = time.perf_counter() + args.deadline
    for t in threads:
        t.join(timeout=max(1.0, deadline - time.perf_counter()))
        if t.is_alive():
            errors.append("client thread past deadline (hang)")


def _run_fleet_kills(args, reqs: list, baseline: dict) -> tuple:
    """Leg 1 + leg 2 over one workdir. Returns (kill_entry,
    cold_entry)."""
    workdir = args.workdir or tempfile.mkdtemp(prefix="dgc_chaos_fleet_")
    os.makedirs(workdir, exist_ok=True)
    journal_dir = os.path.join(workdir, "journal")
    port = _free_port()
    entry = {"kills_planned": int(args.kills), "kills": 0,
             "outcome": "error", "log_problems": 0}
    cold = {"outcome": "error", "log_problems": 0}
    errors: list = []

    # seeded kill plan: each kill fires when the MERGED WAL crosses its
    # offset and takes a seeded replica subset (at least one kill hits
    # >1 replica when the fleet has >1)
    rng = random.Random(args.seed * 104_729 + 7)
    expect = max(6, 2 * len(reqs))
    hi = max(4, expect - 2)
    offsets = sorted(rng.sample(range(2, hi), min(args.kills, hi - 2)))
    subsets = []
    for i in range(len(offsets)):
        size = (max(2, args.replicas) if i == len(offsets) - 1
                and args.replicas > 1 else rng.randint(1, args.replicas))
        subsets.append(sorted(rng.sample(range(args.replicas),
                                         min(size, args.replicas))))
    entry["offsets"] = offsets
    entry["subsets"] = subsets

    log_base = os.path.join(workdir, "fleet.jsonl")
    fleet = _Fleet(port, journal_dir, log_base, args)
    stop_watch = threading.Event()
    kills_done = []

    def watcher():
        plan = list(zip(offsets, subsets))
        while plan and not stop_watch.is_set():
            if _wal_records(journal_dir) >= plan[0][0]:
                _off, subset = plan.pop(0)
                landed = fleet.kill_replicas({f"r{k}" for k in subset})
                kills_done.append({"offset": _off, "subset": subset,
                                   "landed": landed})
            time.sleep(0.005)

    tickets: list = []
    ticket_of: dict = {}
    results: dict = {}
    try:
        fleet.wait_ready()
        watch = threading.Thread(target=watcher, daemon=True)
        watch.start()
        _drive_clients(args, reqs, port, tickets, ticket_of, results,
                       errors)
        stop_watch.set()
        entry["kills"] = len(kills_done)
        entry["kill_detail"] = kills_done

        # -- leg-1 invariants -------------------------------------------
        if len(set(tickets)) != len(tickets):
            errors.append("duplicate ticket ids fleet-wide")
        replicas_seen = {t.split("-")[0] for t in tickets if "-" in t}
        entry["replicas_serving"] = sorted(replicas_seen)
        mismatched = 0
        for ticket, doc in results.items():
            if doc.get("status") != "ok":
                errors.append(f"{ticket}: non-ok terminal "
                              f"{doc.get('status')} ({doc.get('error')})")
            elif doc.get("colors") != baseline[ticket_of[ticket]["seed"]]:
                mismatched += 1
        if len(results) != len(tickets):
            errors.append(f"{len(tickets) - len(results)} tickets never "
                          f"reached a terminal result")
        if mismatched:
            entry["outcome"] = "mismatch"
        elif errors:
            entry["outcome"] = "error"
            entry["errors"] = errors[:8]
        else:
            entry["outcome"] = "ok"

        # -- leg 2: kill-all + cold restart -----------------------------
        cold_errors: list = []
        fleet.kill_all()
        fleet = _Fleet(port, journal_dir, log_base, args)
        fleet.wait_ready()
        stable = 0
        for ticket, doc in results.items():
            if doc.get("status") != "ok":
                continue
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                st, again = _http("GET", port,
                                  f"/v1/result/{ticket}?colors=1",
                                  retries=8, deadline_s=30.0)
                if st == 202:   # replayed by the cold fleet
                    time.sleep(0.05)
                    continue
                if st != 200:
                    cold_errors.append(
                        f"{ticket}: HTTP {st} after cold restart")
                elif again.get("colors") != doc.get("colors"):
                    cold_errors.append(
                        f"{ticket}: colors changed across cold restart")
                else:
                    stable += 1
                break
        cold["tickets_stable"] = stable
        if getattr(args, "result_cache", 0) > 0:
            # cold-cache probe: the fresh fleet's in-memory LRUs are
            # empty, so a re-submitted hot seed must hit the SHARED
            # disk store that survived kill-all — acked cached, colors
            # byte-identical to the fault-free baseline
            probes = 0
            for seed in (11, 22):
                doc = _request_doc(args.nodes, args.degree, seed=seed)
                st, body = _http("POST", port, "/v1/color", doc,
                                 retries=8, deadline_s=30.0)
                if st != 202 or not body.get("cached"):
                    cold_errors.append(f"cold-cache probe seed {seed}: "
                                       f"HTTP {st} cached="
                                       f"{body.get('cached')}")
                    continue
                ticket = body["ticket"]
                t_end = time.perf_counter() + args.deadline
                while time.perf_counter() < t_end:
                    st, res = _http("GET", port,
                                    f"/v1/result/{ticket}?colors=1",
                                    retries=8, deadline_s=30.0)
                    if st != 202:
                        break
                    time.sleep(0.02)
                if st != 200 or res.get("status") != "ok":
                    cold_errors.append(f"cold-cache probe seed {seed}: "
                                       f"terminal HTTP {st}")
                elif res.get("colors") != baseline[seed]:
                    cold_errors.append(f"cold-cache probe seed {seed}: "
                                       f"colors differ from baseline")
                else:
                    probes += 1
            cold["cache_probes_ok"] = probes
        cold.update(_merge_invariants(
            journal_dir, cold_errors,
            expect_cached=getattr(args, "result_cache", 0) > 0))
        try:
            _http("POST", port, "/admin/drain", {}, retries=8,
                  deadline_s=60.0)
            fleet.proc.wait(timeout=90)
        except (RuntimeError, subprocess.TimeoutExpired):
            fleet.proc.kill()
        # the supervisor's per-incarnation logs: validate the ones whose
        # process exited cleanly (killed incarnations may be torn)
        base = log_base[:-len(".jsonl")]
        final_logs = sorted(
            p for p in os.listdir(workdir)
            if p.startswith(os.path.basename(base) + ".r"))
        entry["incarnation_logs"] = len(final_logs)
        cold["outcome"] = "ok" if not cold_errors else "error"
        if cold_errors:
            cold["errors"] = cold_errors[:8]
        return entry, cold
    except RuntimeError as e:
        bad = "hang" if "unreachable" in str(e) \
            or "never became ready" in str(e) else "error"
        if entry["outcome"] == "error":
            entry["outcome"] = bad
            entry["errors"] = [str(e)[:300]]
        else:
            cold["outcome"] = bad
            cold["errors"] = [str(e)[:300]]
        return entry, cold
    finally:
        stop_watch.set()
        if fleet.proc.poll() is None:
            fleet.kill_all()
        if not args.keep_workdir and args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)


def _merge_invariants(journal_dir: str, errors: list,
                      expect_cached: bool = False) -> dict:
    """Cold-fleet merge asserts straight off the journal dir: unique
    ids across ALL namespaces, and PR 16 usage conservation over the
    merged WAL list. With ``expect_cached`` the duplicate-heavy traffic
    mix must have produced at least one cached/coalesced delivery in
    the merged ledger — otherwise the cache arm silently tested
    nothing."""
    from dgc_tpu.obs.usage import conservation_problems, fold_journal
    from dgc_tpu.serve.netfront.journal import (JOURNAL_FILE,
                                                list_namespaces,
                                                scan_fleet)

    out: dict = {}
    scan = scan_fleet(journal_dir)
    ids = [t.ticket for t in scan.state.tickets]
    out["namespaces"] = len(scan.namespaces)
    out["merged_tickets"] = len(ids)
    if len(ids) != len(set(ids)):
        errors.append("fleet merge scan holds duplicate ticket ids")
    torn = [ns for ns, meta in scan.per_namespace.items()
            if meta.get("torn")]
    out["torn_namespaces"] = len(torn)
    wals = [os.path.join(journal_dir, ns, JOURNAL_FILE) if ns
            else os.path.join(journal_dir, JOURNAL_FILE)
            for ns in list_namespaces(journal_dir)]
    rows = fold_journal(wals)
    cons = conservation_problems(rows, wals)
    out["usage_conservation"] = "ok" if not cons else "fail"
    errors.extend(f"usage conservation: {c}" for c in cons[:4])
    cached = sum(int(r.get("cached", 0)) for r in rows)
    out["cached_deliveries"] = cached
    if expect_cached and cached == 0:
        errors.append("result cache armed but zero cached deliveries "
                      "in the merged ledger")
    return out


# ---------------------------------------------------------------------------
# leg 3: brownout tier contract (in-process, deterministic)
# ---------------------------------------------------------------------------

def _run_brownout(args) -> dict:
    """Force a brownout level and prove the tier contract on the wire:
    low tier shed with a structured 503, premium admitted AND served,
    full admission back once the burn clears."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.serve.netfront import (AdmissionController,
                                        BrownoutController, NetFront,
                                        load_tenant_configs)
    from dgc_tpu.serve.queue import ServeFrontEnd

    entry = {"outcome": "error", "log_problems": 0}
    errors: list = []
    workdir = tempfile.mkdtemp(prefix="dgc_chaos_brownout_")
    log = os.path.join(workdir, "brownout.jsonl")
    logger = RunLogger(jsonl_path=log, echo=False)
    bo = BrownoutController(sustain=1, clear=1, logger=logger)
    cfgs = load_tenant_configs({"tenants": {
        "free": {"tier": "free"}, "prem": {"tier": "premium"}}})
    front = nf = None
    doc = _request_doc(args.nodes, args.degree, seed=424_242)
    try:
        front = ServeFrontEnd(batch_max=args.batch_max, window_s=0.0,
                              logger=logger).start()
        nf = NetFront(front, admission=AdmissionController(cfgs),
                      logger=logger, brownout=bo).start()
        bo.on_evaluate(["failure_rate"])            # sustained burn
        st, body = _http("POST", nf.port, "/v1/color", doc,
                         tenant="free", deadline_s=args.deadline)
        if st != 503 or body.get("reason") != "brownout":
            errors.append(f"free tier under burn: HTTP {st} {body}")
        st, body = _http("POST", nf.port, "/v1/color", doc,
                         tenant="prem", deadline_s=args.deadline)
        if st != 202:
            errors.append(f"premium under burn rejected: HTTP {st}")
        else:
            ticket = body["ticket"]
            t_end = time.perf_counter() + args.deadline
            while time.perf_counter() < t_end:
                st, body = _http("GET", nf.port,
                                 f"/v1/result/{ticket}",
                                 deadline_s=args.deadline)
                if st != 202:
                    break
                time.sleep(0.02)
            if st != 200 or body.get("status") != "ok":
                errors.append(f"premium ticket under burn: HTTP {st}")
        bo.on_evaluate([])                          # the burn clears
        st, _body = _http("POST", nf.port, "/v1/color", doc,
                          tenant="free", deadline_s=args.deadline)
        if st != 202:
            errors.append(f"free tier after clear: HTTP {st}")
        entry["shed"] = bo.snapshot()["shed"]
        entry["level_final"] = bo.level()
    except RuntimeError as e:
        errors.append(str(e)[:300])
    finally:
        if nf is not None:
            nf.close()
        if front is not None:
            front.shutdown()
        logger.close()
    entry["log_problems"] = len(validate_file(log))
    events = [json.loads(ln) for ln in open(log) if ln.strip()]
    trans = [(e["action"], e["level"]) for e in events
             if e.get("event") == "net_brownout"]
    if trans != [("shed", 1), ("restore", 0)]:
        errors.append(f"net_brownout transitions {trans}")
    sheds = [e for e in events if e.get("event") == "net_reject"
             and e.get("reason") == "brownout"]
    if any(e.get("tier") not in ("free", "standard") for e in sheds):
        errors.append("brownout shed a non-low tier")
    shutil.rmtree(workdir, ignore_errors=True)
    if errors or entry["log_problems"]:
        entry["outcome"] = "error"
        entry["errors"] = errors[:8]
    else:
        entry["outcome"] = "ok"
    return entry


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def validate_chaos_fleet_report(doc) -> list:
    """Structural check (the chaos_sweep convention: list of problems,
    empty = well-formed)."""
    problems: list = []
    if not isinstance(doc, dict):
        return ["report is not an object"]
    if doc.get("chaos_fleet_report_version") != CHAOS_FLEET_REPORT_VERSION:
        problems.append("missing/wrong chaos_fleet_report_version")
    if not isinstance(doc.get("config"), dict):
        problems.append("missing config object")
    for leg in ("kill_resume", "cold_restart", "brownout"):
        ent = doc.get(leg)
        if ent is None:
            continue
        if not isinstance(ent, dict):
            problems.append(f"{leg}: not an object")
            continue
        if ent.get("outcome") not in _OUTCOMES:
            problems.append(f"{leg}: unknown outcome "
                            f"{ent.get('outcome')!r}")
    kr = doc.get("kill_resume")
    if kr is not None:
        for fieldname in ("kills_planned", "kills"):
            if not isinstance(kr.get(fieldname), int):
                problems.append(
                    f"kill_resume: missing/invalid {fieldname!r}")
    cfg = doc.get("config")
    cr = doc.get("cold_restart")
    if (isinstance(cfg, dict) and cfg.get("result_cache", 0)
            and cr is not None
            and not isinstance(cr.get("cached_deliveries"), int)):
        problems.append("cold_restart: result cache armed but "
                        "missing/invalid 'cached_deliveries'")
    summary = doc.get("summary")
    if not isinstance(summary, dict):
        problems.append("missing summary object")
    else:
        for fieldname in ("total", "ok", "failed"):
            if not isinstance(summary.get(fieldname), int):
                problems.append(f"summary: missing/invalid {fieldname!r}")
    return problems


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--replicas", type=int, default=2,
                   help="fleet width under test (default 2)")
    p.add_argument("--kills", type=int, default=2,
                   help="seeded replica-subset SIGKILLs at merged-WAL "
                        "offsets (0 skips legs 1+2)")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--requests-per-client", type=int, default=2)
    p.add_argument("--nodes", type=int, default=300,
                   help="vertices per generated request")
    p.add_argument("--degree", type=int, default=6)
    p.add_argument("--batch-max", type=int, default=4)
    p.add_argument("--seed", type=int, default=0,
                   help="master seed: kill offsets AND replica subsets "
                        "derive from it deterministically")
    p.add_argument("--dispatch-timeout", type=float, default=3.0)
    p.add_argument("--max-lane-aborts", type=int, default=3)
    p.add_argument("--result-cache", type=int, default=0, metavar="N",
                   help="arm the serve-tier result cache (per-replica "
                        "LRU of N + shared disk store) and switch the "
                        "traffic mix duplicate-heavy: cached hits and "
                        "coalesced flights must survive kills and cold "
                        "restart byte-identical to the fault-free "
                        "baseline (0 = off)")
    p.add_argument("--skip-brownout", action="store_true",
                   help="skip leg 3 (the in-process brownout contract)")
    p.add_argument("--deadline", type=float, default=240.0,
                   help="per-leg hard deadline; a run past it is a "
                        "chaos failure (hang)")
    p.add_argument("--report", default="chaos_fleet_report.json")
    p.add_argument("--workdir", default=None)
    p.add_argument("--keep-workdir", action="store_true")
    args = p.parse_args(argv)
    if args.replicas < 2:
        print("--replicas must be >= 2 (that is the point)",
              file=sys.stderr)
        return 2

    reqs = [_request_doc(args.nodes, args.degree, seed=c * 10_000 + r)
            for c in range(args.clients)
            for r in range(args.requests_per_client)]
    if args.result_cache > 0:
        # duplicate-heavy mix: every other request re-submits one of
        # two hot seeds, so kills land across cache hits and coalesced
        # flights too. The baseline dict is keyed by seed, so the
        # byte-identity assert covers cached deliveries for free.
        pool = (11, 22)
        for i in range(1, len(reqs), 2):
            reqs[i] = _request_doc(args.nodes, args.degree,
                                   seed=pool[(i // 2) % len(pool)])
    print(f"# chaos_fleet: {len(reqs)} requests V={args.nodes} "
          f"replicas={args.replicas} seed={args.seed} "
          f"kills={args.kills} result_cache={args.result_cache}",
          file=sys.stderr)

    kill_resume = cold_restart = None
    if args.kills > 0:
        baseline = _baseline_colors(args, reqs)
        print(f"# chaos_fleet: fault-free baseline captured "
              f"({len(baseline)} colorings)", file=sys.stderr)
        kill_resume, cold_restart = _run_fleet_kills(args, reqs, baseline)
        print(f"# kill-resume: {kill_resume['outcome']} "
              f"kills={kill_resume['kills']}/"
              f"{kill_resume['kills_planned']}", file=sys.stderr)
        print(f"# cold-restart: {cold_restart['outcome']} "
              f"stable={cold_restart.get('tickets_stable')} "
              f"namespaces={cold_restart.get('namespaces')}",
              file=sys.stderr)

    brownout = None
    if not args.skip_brownout:
        brownout = _run_brownout(args)
        print(f"# brownout: {brownout['outcome']} "
              f"shed={brownout.get('shed')}", file=sys.stderr)

    legs = [e for e in (kill_resume, cold_restart, brownout)
            if e is not None]
    ok = sum(1 for e in legs if e["outcome"] == "ok")
    failed = len(legs) - ok
    report = {
        "chaos_fleet_report_version": CHAOS_FLEET_REPORT_VERSION,
        "config": {"replicas": args.replicas, "kills": args.kills,
                   "clients": args.clients,
                   "requests_per_client": args.requests_per_client,
                   "nodes": args.nodes, "degree": args.degree,
                   "seed": args.seed, "batch_max": args.batch_max,
                   "result_cache": args.result_cache},
        "kill_resume": kill_resume,
        "cold_restart": cold_restart,
        "brownout": brownout,
        "summary": {"total": len(legs), "ok": ok, "failed": failed},
    }
    problems = validate_chaos_fleet_report(report)
    if problems:
        for prob in problems:
            print(f"# chaos_fleet report malformed: {prob}",
                  file=sys.stderr)
        failed += 1
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"chaos_fleet": {
        "total": report["summary"]["total"], "ok": ok, "failed": failed,
        "report": args.report}}))
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())

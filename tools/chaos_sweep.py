#!/usr/bin/env python
"""Chaos harness: soak the resilience subsystem under seeded fault schedules.

Runs the CLI sweep N times, each under a different deterministic fault
schedule (transient device errors, simulated OOM, checkpoint
truncation/corruption, kill-mid-sweep, hangs), and asserts the resilience
invariant for every run:

    the run either finishes with a valid coloring **bit-identical to the
    fault-free run of whichever engine produced it**, or exits with a
    structured abort (rc 114) / watchdog abort (rc 113) — never a garbage
    coloring, never a hang past the harness deadline, never an
    unclassified crash.

"Whichever engine produced it": retries and kill/resume recover on the
primary backend, so those runs compare against the primary's fault-free
coloring; a run that degraded down the engine ladder compares against the
fault-free run of the rung it landed on (engine families are not
per-vertex identical to each other — SURVEY §7.3 — but each engine is
deterministic, so recovery must be invisible relative to its own
fault-free output). A killed process (rc 137) is restarted the way an
operator would — same command, same checkpoint dir, no fault schedule —
and must resume to the identical result.

Every run's JSONL log is schema-checked with ``tools/validate_runlog.py``
(the obs drift guard), and the chaos report itself is schema-checked by
:func:`validate_chaos_report` before it is written.

Usage::

    python tools/chaos_sweep.py --schedules 20 --nodes 1000 --max-degree 8 \\
        --backend ell --report /tmp/chaos.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dgc_tpu.resilience.faults import KILL_RC, FaultSchedule  # noqa: E402
from dgc_tpu.resilience.supervisor import STRUCTURED_ABORT_RC  # noqa: E402
from dgc_tpu.utils.watchdog import ABORT_RC  # noqa: E402
from tools.validate_runlog import validate_file  # noqa: E402

CHAOS_REPORT_VERSION = 1

# acceptable terminal states (everything else is a chaos failure)
_OUTCOMES = ("ok", "structured_abort", "watchdog_abort",
             "hang", "error", "mismatch")


def _subprocess_env() -> dict:
    """CPU-pinned, axon-sitecustomize-free env for CLI subprocesses (the
    proven pattern from tests/test_cli_watchdog.py)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_cli(argv: list[str], timeout_s: float) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "dgc_tpu.cli", *argv],
        env=_subprocess_env(), cwd=REPO, capture_output=True, text=True,
        timeout=timeout_s,
    )


def _final_backend(log_path: str, primary: str) -> str:
    """The engine that produced the run's output: the last fallback
    event's target, or the primary backend when no fallback fired."""
    backend = primary
    try:
        with open(log_path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("event") == "fallback":
                    backend = rec.get("to_backend", backend)
    except OSError:
        pass
    return backend


def validate_chaos_report(doc) -> list[str]:
    """Structural check of a chaos report (the runlog-validator convention:
    a list of problems, empty = well-formed)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["report is not an object"]
    if doc.get("chaos_report_version") != CHAOS_REPORT_VERSION:
        problems.append("missing/wrong chaos_report_version")
    if not isinstance(doc.get("config"), dict):
        problems.append("missing config object")
    schedules = doc.get("schedules")
    if not isinstance(schedules, list) or not schedules:
        problems.append("missing/empty schedules list")
        schedules = []
    for i, s in enumerate(schedules):
        for field, ty in (("index", int), ("spec", str), ("outcome", str),
                          ("rc", int), ("restarts", int),
                          ("final_backend", str)):
            if not isinstance(s.get(field), ty):
                problems.append(f"schedules[{i}]: missing/invalid {field!r}")
        if s.get("outcome") not in _OUTCOMES:
            problems.append(f"schedules[{i}]: unknown outcome {s.get('outcome')!r}")
        if s.get("outcome") == "ok" and s.get("bit_identical") is not True:
            problems.append(f"schedules[{i}]: outcome ok but not bit_identical")
    summary = doc.get("summary")
    if not isinstance(summary, dict):
        problems.append("missing summary object")
    else:
        for field in ("total", "ok", "structured_abort", "failed"):
            if not isinstance(summary.get(field), int):
                problems.append(f"summary: missing/invalid {field!r}")
        if isinstance(schedules, list) and summary.get("total") != len(schedules):
            problems.append("summary.total != len(schedules)")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--schedules", type=int, default=20,
                   help="number of seeded fault schedules to soak")
    p.add_argument("--nodes", type=int, default=1000)
    p.add_argument("--max-degree", type=int, default=8)
    p.add_argument("--seed", type=int, default=0,
                   help="master seed: graph AND every fault schedule derive "
                        "from it deterministically")
    p.add_argument("--backend", default="ell",
                   help="primary engine under test (default: ell)")
    p.add_argument("--fallback-ladder", default=None,
                   help="forwarded to the CLI (default: canonical ladder)")
    p.add_argument("--retries", type=int, default=3)
    p.add_argument("--attempt-timeout", type=float, default=6.0)
    p.add_argument("--max-faults", type=int, default=3,
                   help="max faults drawn per schedule")
    p.add_argument("--run-deadline", type=float, default=180.0,
                   help="hard per-subprocess deadline (a run past it is a "
                        "chaos failure: hang past the watchdog)")
    p.add_argument("--report", default="chaos_report.json")
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    p.add_argument("--keep-workdir", action="store_true")
    args = p.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="dgc_chaos_")
    os.makedirs(workdir, exist_ok=True)
    graph_path = os.path.join(workdir, "graph.json")

    from dgc_tpu.models.graph import Graph

    Graph.generate(args.nodes, args.max_degree, seed=args.seed,
                   method="reference").serialize(graph_path)
    print(f"# chaos: graph V={args.nodes} maxdeg={args.max_degree} "
          f"seed={args.seed} backend={args.backend} workdir={workdir}",
          file=sys.stderr)

    baselines: dict[str, list] = {}

    def baseline_colors(backend: str) -> list:
        """Fault-free, resilience-off (pre-PR dispatch chain) reference
        coloring for one backend, computed once."""
        if backend not in baselines:
            out = os.path.join(workdir, f"baseline_{backend}.json")
            r = _run_cli(["--input", graph_path, "--output-coloring", out,
                          "--backend", backend],
                         timeout_s=args.run_deadline)
            if r.returncode != 0:
                raise RuntimeError(
                    f"fault-free baseline for {backend} failed rc "
                    f"{r.returncode}:\n{r.stderr}")
            baselines[backend] = json.load(open(out))
        return baselines[backend]

    results = []
    for i in range(args.schedules):
        rng = random.Random(args.seed * 100_003 + i)
        schedule = FaultSchedule.random(
            rng, n_faults=rng.randint(1, args.max_faults),
            hang_seconds=args.attempt_timeout + 2.0)
        spec = schedule.to_spec()
        out = os.path.join(workdir, f"colors_{i}.json")
        log = os.path.join(workdir, f"run_{i}.jsonl")
        ckpt = os.path.join(workdir, f"ckpt_{i}")
        base_cmd = ["--input", graph_path, "--output-coloring", out,
                    "--backend", args.backend,
                    "--retries", str(args.retries),
                    "--attempt-timeout", str(args.attempt_timeout),
                    "--checkpoint-dir", ckpt, "--log-json", log]
        if args.fallback_ladder:
            base_cmd += ["--fallback-ladder", args.fallback_ladder]

        entry = {"index": i, "spec": spec, "restarts": 0,
                 "final_backend": args.backend, "bit_identical": None,
                 "log_problems": 0}
        try:
            r = _run_cli(base_cmd + ["--inject-faults", spec],
                         timeout_s=args.run_deadline)
            rc = r.returncode
            # an injected kill (rc 137) is what an operator restart cures:
            # rerun the same command — checkpoint intact, no fault plane
            while rc == KILL_RC and entry["restarts"] < 3:
                entry["restarts"] += 1
                r = _run_cli(base_cmd, timeout_s=args.run_deadline)
                rc = r.returncode
        except subprocess.TimeoutExpired:
            entry.update(outcome="hang", rc=-1)
            results.append(entry)
            print(f"# [{i}] HANG  spec={spec}", file=sys.stderr)
            continue

        entry["rc"] = rc
        entry["log_problems"] = len(validate_file(log)) if os.path.exists(log) else 0
        if rc == 0:
            final = _final_backend(log, args.backend)
            entry["final_backend"] = final
            identical = json.load(open(out)) == baseline_colors(final)
            entry["bit_identical"] = identical
            entry["outcome"] = "ok" if identical and not entry["log_problems"] \
                else "mismatch"
        elif rc == STRUCTURED_ABORT_RC:
            entry["outcome"] = "structured_abort"
        elif rc == ABORT_RC:
            entry["outcome"] = "watchdog_abort"
        else:
            entry["outcome"] = "error"
        results.append(entry)
        print(f"# [{i}] {entry['outcome']:<16} rc={rc} restarts="
              f"{entry['restarts']} engine={entry['final_backend']} "
              f"spec={spec}", file=sys.stderr)

    ok = sum(1 for e in results if e["outcome"] == "ok")
    aborts = sum(1 for e in results
                 if e["outcome"] in ("structured_abort", "watchdog_abort"))
    failed = len(results) - ok - aborts
    report = {
        "chaos_report_version": CHAOS_REPORT_VERSION,
        "config": {"schedules": args.schedules, "nodes": args.nodes,
                   "max_degree": args.max_degree, "seed": args.seed,
                   "backend": args.backend, "retries": args.retries,
                   "attempt_timeout": args.attempt_timeout,
                   "fallback_ladder": args.fallback_ladder},
        "schedules": results,
        "summary": {"total": len(results), "ok": ok,
                    "structured_abort": aborts, "failed": failed},
    }
    problems = validate_chaos_report(report)
    if problems:
        for prob in problems:
            print(f"# chaos report malformed: {prob}", file=sys.stderr)
        failed += 1  # a malformed report is itself a harness failure
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"chaos": {"total": len(results), "ok": ok,
                                "aborts": aborts, "failed": failed,
                                "report": args.report}}))
    if not args.keep_workdir and args.workdir is None:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())

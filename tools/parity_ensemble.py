"""Scale parity ensemble: flagship-family engine + recolor pass vs the
reference semantics (vectorized ``ReferenceSimEngine``), many draws.

The one-sided contract under test (BASELINE.md round-4 amendment): the
engine's final color count must never exceed the reference's + 1; lower
is an improvement. This tool makes the contract checkable at scales the
loop-form sim made impractical (VERDICT r4 weak #6 / next #4):

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/parity_ensemble.py \
        --nodes 50000 --draws 30 --out tools/parity_50k.jsonl

Engine: bucketed ELL (bit-identical counts to every other array engine —
the speculative rule is single-sourced in ``ops.speculative``), chosen
because its quantized bucket shapes reuse compiled executables across
draws on CPU. Emits one JSON line per draw and a final summary line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, required=True)
    p.add_argument("--draws", type=int, default=30)
    p.add_argument("--avg-degree", type=float, default=16.0)
    p.add_argument("--gen", choices=["rmat", "uniform"], default="rmat",
                   help="graph family: power-law RMAT (heavy tail) or "
                        "uniform random (the BASELINE headline family)")
    p.add_argument("--seed0", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    args = p.parse_args()

    import jax

    from dgc_tpu.engine.bucketed import BucketedELLEngine
    from dgc_tpu.engine.minimal_k import (find_minimal_coloring, make_reducer,
                                          make_validator)
    from dgc_tpu.engine.reference_sim import ReferenceSimEngine
    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)

    # mode "w": the artifact is one run's evidence — appending across runs
    # (possibly across code versions) would make the summary contradict
    # the records above it
    out = open(args.out, "w") if args.out else None
    gaps: list[int] = []
    t_all = time.perf_counter()
    try:
        for i in range(args.draws):
            seed = args.seed0 + i
            if args.gen == "uniform":
                g = generate_random_graph_fast(args.nodes,
                                               avg_degree=args.avg_degree,
                                               seed=seed)
            else:
                g = generate_rmat_graph(args.nodes, avg_degree=args.avg_degree,
                                        seed=seed)
            t0 = time.perf_counter()
            a = find_minimal_coloring(BucketedELLEngine(g), g.max_degree + 1,
                                      validate=make_validator(g),
                                      post_reduce=make_reducer(g))
            t_eng = time.perf_counter() - t0
            t0 = time.perf_counter()
            b = find_minimal_coloring(ReferenceSimEngine(g), g.max_degree + 1,
                                      validate=make_validator(g))
            t_ref = time.perf_counter() - t0
            gap = a.minimal_colors - b.minimal_colors
            gaps.append(gap)
            rec = {"nodes": args.nodes, "gen": args.gen, "seed": seed,
                   "max_degree": int(g.max_degree),
                   "engine_colors": a.minimal_colors, "ref_colors": b.minimal_colors,
                   "gap": gap, "engine_s": round(t_eng, 1), "ref_s": round(t_ref, 1)}
            line = json.dumps(rec)
            print(line, flush=True)
            if out:
                out.write(line + "\n")
                out.flush()
            if i % 5 == 4:
                jax.clear_caches()  # bound per-shape executable footprint
    finally:
        # an interrupted run still gets a (partial=true) verdict line, so
        # the artifact is never a bare list with no contract verdict
        hist: dict[int, int] = {}
        for gp in gaps:
            hist[gp] = hist.get(gp, 0) + 1
        summary = {
            "summary": True, "nodes": args.nodes, "gen": args.gen,
            "draws": len(gaps), "draws_requested": args.draws,
            "partial": len(gaps) < args.draws,
            "gap_hist": {str(kk): hist[kk] for kk in sorted(hist)},
            "max_gap": max(gaps) if gaps else None,
            "le_ref": sum(1 for gp in gaps if gp <= 0),
            "contract_ok": bool(gaps) and max(gaps) <= 1,
            "total_s": round(time.perf_counter() - t_all, 1),
        }
        line = json.dumps(summary)
        print(line, flush=True)
        if out:
            out.write(line + "\n")
            out.close()
    return 0 if summary["contract_ok"] and not summary["partial"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Export per-tenant usage rollups from a durable ticket journal.

The offline half of the fleet's usage metering (``dgc_tpu.obs.usage``):
fold a serve tier's ticket journal directory (``--journal-dir`` /
``tools/chaos_serve.py`` workdirs) — plus optional run-log JSONLs for
the kernel device-time column — into one ``usage_rollup`` row per
tenant, written as a versioned JSONL artifact. Each row is emitted as a
schema-valid ``usage_rollup`` event (``tools/validate_runlog.py``
validates the artifact like any run log).

The fold is crash-resume exact: ``scan_journal`` dedups every lifecycle
stage by ticket id, so a kill-resume soak's N incarnations over one
journal meter each ticket once. ``--check`` proves it — the per-tenant
sums are recomputed against the journal's RAW record totals
(``obs.usage.journal_totals``, an independent derivation) and any
inequality exits nonzero. Conservation is exact equality, not a
tolerance: billing rows that "mostly" add up are wrong.

Usage:
    python tools/usage_export.py JOURNAL_DIR -o usage.jsonl
    python tools/usage_export.py JOURNAL_DIR --logs 'server_*.jsonl' \\
        --check          # conservation-gated export (CI smoke)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dgc_tpu.obs.usage import (conservation_problems,  # noqa: E402
                               fold_journal, journal_totals)
from dgc_tpu.serve.netfront.journal import JOURNAL_FILE  # noqa: E402


def export_rows(journal_dir: str, log_globs=()) -> list:
    """Per-tenant ``usage_rollup`` rows for one journal directory; log
    globs feed the device-time column."""
    journal_path = os.path.join(journal_dir, JOURNAL_FILE)
    log_paths: list = []
    for pattern in log_globs:
        log_paths.extend(sorted(glob.glob(pattern)))
    return fold_journal(journal_path, log_paths=log_paths)


def write_artifact(rows: list, out_path: str) -> None:
    """The versioned JSONL artifact: one schema-valid ``usage_rollup``
    event per tenant (``t`` is export wall time — rows are totals, not
    a timeline)."""
    t = round(time.time(), 6)
    with open(out_path, "w") as fh:

        def event(kind: str, **fields) -> None:
            fh.write(json.dumps({"t": t, "event": kind, **fields})
                     + "\n")

        for row in rows:
            event("usage_rollup", **row)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("journal_dir",
                   help="ticket journal directory (the serve CLI's "
                        "--journal-dir)")
    p.add_argument("--logs", action="append", default=[],
                   metavar="GLOB",
                   help="run-log JSONL glob(s) for the per-tenant "
                        "device-time column (e.g. 'server_*.jsonl'); "
                        "repeatable")
    p.add_argument("-o", "--out", default=None,
                   help="write the rollup JSONL artifact here "
                        "(default: stdout)")
    p.add_argument("--check", action="store_true",
                   help="conservation gate: per-tenant sums must "
                        "EXACTLY equal the journal's raw totals, else "
                        "exit 1")
    args = p.parse_args(argv)
    journal_path = os.path.join(args.journal_dir, JOURNAL_FILE)
    if not os.path.exists(journal_path):
        print(f"error: no {JOURNAL_FILE} in {args.journal_dir}",
              file=sys.stderr)
        return 2
    try:
        rows = export_rows(args.journal_dir, log_globs=args.logs)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.out:
        write_artifact(rows, args.out)
    else:
        for row in rows:
            print(json.dumps(row))
    totals = journal_totals(journal_path)
    print(f"# {len(rows)} tenant(s); journal totals: "
          f"{totals['admitted']} admitted, {totals['delivered']} "
          f"delivered, {totals['failed']} failed, "
          f"{totals['aborted']} aborted", file=sys.stderr)
    if args.check:
        problems = conservation_problems(rows, journal_path)
        for problem in problems:
            print(f"CHECK FAIL: {problem}", file=sys.stderr)
        if problems:
            return 1
        print("# conservation: per-tenant sums equal journal totals "
              "exactly", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

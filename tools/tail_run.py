#!/usr/bin/env python
"""Live-tail a JSONL run log and render the report while the run runs.

``tools/report_run.py`` renders a finished artifact; this follows a
growing ``--log-json`` stream (a sweep mid-flight, a serve loop under
load) and re-renders the same report incrementally: new lines are fed
through the identical ``RunManifest`` sink, so the live view and the
post-hoc report can never disagree (continuous-mode serve runs get the
lane-occupancy, staged-ladder rung/stage-occupancy, and host↔device
transfer series live). The ROADMAP telemetry follow-on ("live
tailing").

    python tools/tail_run.py RUN.jsonl              # follow until done
    python tools/tail_run.py RUN.jsonl --once       # render now, exit

Follow mode clears the screen between frames (disable with
``--no-clear``), exits when the stream reaches a terminal event
(``sweep_done`` / ``sweep_failed`` / ``serve_summary`` /
``structured_abort``) plus ``--grace`` seconds, or on Ctrl-C. A log
path that does not exist yet is waited for — start the tail before the
run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.obs.manifest import RunManifest  # noqa: E402
from tools.report_run import render  # noqa: E402

_TERMINAL = {"sweep_done", "sweep_failed", "serve_summary",
             "structured_abort", "watchdog_abort"}


class LogFollower:
    """Incremental JSONL reader feeding a ``RunManifest`` sink.

    Tolerates a partially-written last line (no trailing newline yet):
    it stays buffered until the writer finishes it. ``poll()`` returns
    the number of new events consumed; ``done`` flips on a terminal
    event."""

    def __init__(self, path: str):
        self.path = path
        self.manifest = RunManifest()
        self.done = False
        self.events = 0
        self._pos = 0
        self._buf = ""

    def poll(self) -> int:
        try:
            with open(self.path) as fh:
                fh.seek(self._pos)
                chunk = fh.read()
                self._pos = fh.tell()
        except OSError:
            return 0
        if not chunk:
            return 0
        self._buf += chunk
        new = 0
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write; the writer re-emits whole lines only
            self.manifest(record)
            new += 1
            self.events += 1
            if record.get("event") in _TERMINAL:
                self.done = True
        return new


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="JSONL run log (--log-json output)")
    p.add_argument("--once", action="store_true",
                   help="render the current state once and exit (tests)")
    p.add_argument("--interval", type=float, default=0.5,
                   help="poll interval in seconds (default 0.5)")
    p.add_argument("--width", type=int, default=48,
                   help="sparkline width (report_run contract)")
    p.add_argument("--no-clear", action="store_true",
                   help="append frames instead of clearing the screen")
    p.add_argument("--grace", type=float, default=1.0,
                   help="seconds to keep tailing after a terminal event "
                        "(late trajectory/manifest lines)")
    p.add_argument("--timeout", type=float, default=0.0,
                   help="give up after this many seconds (0 = forever)")
    args = p.parse_args(argv)

    follower = LogFollower(args.path)
    if args.once:
        follower.poll()
        sys.stdout.write(render(follower.manifest.doc, width=args.width))
        return 0

    t0 = time.monotonic()
    t_done = None
    try:
        while True:
            new = follower.poll()
            if new:
                frame = render(follower.manifest.doc, width=args.width)
                if not args.no_clear:
                    sys.stdout.write("\x1b[2J\x1b[H")
                sys.stdout.write(
                    frame + f"[tail] {follower.events} events from "
                            f"{args.path}\n")
                sys.stdout.flush()
            if follower.done:
                if t_done is None:
                    t_done = time.monotonic()
                elif time.monotonic() - t_done >= args.grace:
                    return 0
            if args.timeout and time.monotonic() - t0 > args.timeout:
                print(f"[tail] timeout after {args.timeout:g}s",
                      file=sys.stderr)
                return 3
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""SLO gate: check a serve run's artifact against a thresholds file.

Exit 0 when every objective holds, 1 on any violation (each printed to
stderr), 2 on usage/load errors — the perf-regression tripwire
``bench.py --serve-modes --slo-thresholds`` and the evidence suite run
against the committed BENCH/BASELINE trajectory.

Input: a run manifest (``--run-manifest``) or a raw JSONL run log
(replayed through the same ``RunManifest`` sink, the ``report_run``
convention). Latency percentiles are computed from the manifest's exact
per-request records (``serve.requests[*].service_ms/queue_ms``,
linear-interpolated — the same estimator NumPy's default percentile
uses), falling back to the metrics snapshot's bucket-interpolated
histograms when the request list is absent.

Thresholds file (JSON; every key optional — absent means unchecked):

    {
      "service_ms": {"p50": 100, "p95": 250, "p99": 400},
      "queue_ms":   {"p95": 50},
      "graphs_per_s_min": 0.5,
      "failure_rate_max": 0.0,
      "classes": {"v32768w64": {"service_ms": {"p95": 300}}}
    }

Top-level ``service_ms``/``queue_ms`` gate the whole request population;
``classes`` adds per-shape-class gates over that class's requests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.obs.manifest import RunManifest, load_manifest  # noqa: E402

_QUANTS = {"p50": 0.50, "p95": 0.95, "p99": 0.99}


def percentile(values: list, q: float) -> float | None:
    """Linear-interpolated percentile of a sample (NumPy's default
    method, dependency-free)."""
    if not values:
        return None
    xs = sorted(float(v) for v in values)
    if len(xs) == 1:
        return xs[0]
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


def load_doc(path: str) -> dict:
    if path.endswith(".jsonl"):
        manifest = RunManifest()
        with open(path) as fh:
            raw = fh.read()
        lines = raw.split("\n")
        torn_tail = not raw.endswith("\n")
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                manifest(json.loads(line))
            except json.JSONDecodeError:
                if torn_tail and i == len(lines) - 1:
                    continue   # live log mid-write
                raise
        return manifest.doc
    return load_manifest(path)


def _gate_latencies(violations: list, label: str, thresholds: dict,
                    samples: dict) -> None:
    """Check {metric: {pXX: limit}} thresholds against {metric: values}."""
    for metric in ("service_ms", "queue_ms"):
        limits = thresholds.get(metric)
        if not limits:
            continue
        values = samples.get(metric) or []
        if not values:
            violations.append(
                f"{label}: {metric} thresholds given but no samples")
            continue
        for pname, limit in limits.items():
            q = _QUANTS.get(pname)
            if q is None:
                violations.append(
                    f"{label}: unknown quantile {pname!r} "
                    f"(use {sorted(_QUANTS)})")
                continue
            got = percentile(values, q)
            if got > float(limit):
                violations.append(
                    f"{label}: {metric} {pname} = {got:.1f} ms "
                    f"> {float(limit):.1f} ms "
                    f"(n={len(values)})")


def _histogram_samples(doc: dict) -> dict:
    """Fallback when the manifest carries no request list: approximate
    the overall population from the metrics snapshot's
    ``dgc_serve_service_seconds`` histograms via bucket interpolation
    (``obs.metrics.Histogram`` semantics) — returns {metric: values}
    shaped like request samples by expanding each bucket at its
    interpolation midpoint."""
    metrics = doc.get("metrics") or {}
    out: dict = {"service_ms": [], "queue_ms": []}
    names = {"dgc_serve_service_seconds": "service_ms",
             "dgc_serve_queue_seconds": "queue_ms"}
    for key, snap in metrics.items():
        base = key.split("{", 1)[0]
        metric = names.get(base)
        if metric is None or snap.get("kind") != "histogram":
            continue
        lo = 0.0
        for edge, count in snap.get("buckets", {}).items():
            hi = float(edge)
            out[metric].extend([(lo + hi) / 2 * 1e3] * int(count))
            lo = hi
        out[metric].extend([lo * 1e3] * int(snap.get("inf", 0)))
    return out


def check_serve_doc(doc: dict, thresholds: dict) -> list[str]:
    """All SLO violations of one run document (empty = pass)."""
    violations: list[str] = []
    serve = doc.get("serve") or {}
    requests = [r for r in (serve.get("requests") or [])
                if r.get("status") != "rejected"]
    if requests:
        samples = {
            "service_ms": [r["service_ms"] for r in requests
                           if r.get("service_ms") is not None],
            "queue_ms": [r["queue_ms"] for r in requests
                         if r.get("queue_ms") is not None],
        }
    else:
        samples = _histogram_samples(doc)
    _gate_latencies(violations, "overall", thresholds, samples)

    for cls, sub in (thresholds.get("classes") or {}).items():
        cls_reqs = [r for r in requests if r.get("shape_class") == cls]
        _gate_latencies(
            violations, f"class {cls}", sub,
            {"service_ms": [r["service_ms"] for r in cls_reqs
                            if r.get("service_ms") is not None],
             "queue_ms": [r["queue_ms"] for r in cls_reqs
                          if r.get("queue_ms") is not None]})

    summary = serve.get("summary") or {}
    gps_min = thresholds.get("graphs_per_s_min")
    if gps_min is not None:
        gps = summary.get("graphs_per_s")
        if gps is None:
            violations.append("graphs_per_s_min given but the run has no "
                              "serve summary throughput")
        elif gps < float(gps_min):
            violations.append(f"throughput: {gps} graphs/s "
                              f"< {float(gps_min)} graphs/s")
    fail_max = thresholds.get("failure_rate_max")
    if fail_max is not None:
        total = summary.get("requests") or len(requests)
        failed = summary.get("failed")
        if failed is None:
            failed = sum(1 for r in requests if r.get("status") != "ok")
        if total:
            rate = failed / total
            if rate > float(fail_max):
                violations.append(
                    f"failure rate: {failed}/{total} = {rate:.3f} "
                    f"> {float(fail_max)}")
    return violations


def check_bench_record(record: dict, thresholds: dict) -> list[str]:
    """The bench-tripwire variant: gate one ``bench.py --serve-modes``
    JSON record (graphs/s headline + speedup) against the same
    thresholds file — ``graphs_per_s_min`` and
    ``speedup_vs_sequential_min`` apply."""
    violations: list[str] = []
    gps_min = thresholds.get("graphs_per_s_min")
    if gps_min is not None and record.get("value") is not None:
        if record["value"] < float(gps_min):
            violations.append(
                f"bench throughput: {record['value']} graphs/s "
                f"< {float(gps_min)} graphs/s")
    sp_min = thresholds.get("speedup_vs_sequential_min")
    if sp_min is not None:
        sp = record.get("speedup_vs_sequential")
        if sp is None:
            violations.append("speedup_vs_sequential_min given but the "
                              "record has no speedup")
        elif sp < float(sp_min):
            violations.append(f"bench speedup: {sp}x sequential "
                              f"< {float(sp_min)}x")
    return violations


class ViolationHooks:
    """What to do the instant an SLO gate trips (PR 11 retrospective
    layer): dump the flight recorder's event tail and/or open a short
    profiler window over whatever the process is still executing.

    In-process gates (``bench.py --slo-thresholds``, a live serve loop
    checking itself) construct one and call :meth:`fire` with the
    violation list; the standalone post-hoc CLI has nothing live to
    capture and never fires hooks. Both actions are best-effort — a
    diagnostics failure must never mask the violation exit code."""

    def __init__(self, *, recorder=None, dump_dir: str = ".",
                 profile_logdir: str | None = None,
                 profile_ms: float = 0.0, logger=None):
        self.recorder = recorder
        self.dump_dir = dump_dir
        self.profile_logdir = profile_logdir
        self.profile_ms = float(profile_ms)
        self.logger = logger

    def fire(self, violations: list) -> dict:
        """Returns {"dump": path|None, "profile": fields|None}."""
        out: dict = {"dump": None, "profile": None}
        if not violations:
            return out
        if self.recorder is not None:
            try:
                out["dump"] = self.recorder.dump(
                    self.dump_dir, reason="slo_violation",
                    trigger=violations[0], logger=self.logger)
            except OSError as e:
                print(f"# slo hooks: flightrec dump failed: {e}",
                      file=sys.stderr)
        if self.profile_ms > 0 and self.profile_logdir:
            from dgc_tpu.obs import profiler

            out["profile"] = profiler.timed_window(
                self.profile_logdir, self.profile_ms,
                trigger="slo_violation", logger=self.logger)
        return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="run manifest JSON or JSONL run log")
    p.add_argument("--thresholds", required=True,
                   help="SLO thresholds JSON (module docstring schema)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the PASS line")
    args = p.parse_args(argv)
    try:
        doc = load_doc(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot load {args.path}: {e}", file=sys.stderr)
        return 2
    try:
        thresholds = json.loads(open(args.thresholds).read())
        if not isinstance(thresholds, dict):
            raise ValueError("thresholds must be a JSON object")
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot load {args.thresholds}: {e}", file=sys.stderr)
        return 2
    violations = check_serve_doc(doc, thresholds)
    if violations:
        for v in violations:
            print(f"SLO VIOLATION: {v}", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"{args.path}: SLO PASS ({args.thresholds})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Xplane-trace attribution for the staged kernels' gather-rate gap.

PERF.md's audits price sweeps in element gathers; the conversion to
seconds uses an *effective* ~45-55M lookups/s measured end-to-end — half
the raw 100-140M/s large-gather rate (``tools/rate_probe.py``). This tool
attributes the loss with a real profile instead of inference: it runs one
k-attempt under ``jax.profiler.trace`` and aggregates device-plane XLA op
time by category, so the question "is the lost time inside the gather
fusions themselves, between them (scheduling/cond gaps), or in
non-gather machinery?" gets a measured answer.

Usage — on the chip (the real use), run with the image's default env:

    python tools/trace_attempt.py [--nodes N] [--gen rmat|fast]

For CPU plumbing tests, scrub the sitecustomize path or the process dials
the TPU tunnel regardless of JAX_PLATFORMS (see .claude/skills/verify):

    PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/trace_attempt.py \
        [--nodes N] [--gen rmat|fast]
        [--backend ell-compact|ell-bucketed|ell] [--avg-degree D]
        [--seed S] [--logdir DIR] [--top N]

Prints one JSON object: total device time, a category breakdown
(segmented-gather / gather / scatter / while-overhead / collectives /
elementwise-fusion / copy / other), idle time (trace span − Σop), and the
top-N ops. ``segmented-gather`` is the fused O(1)-per-superstep gather of
the segmented plan (``ops.segmented_gather``, named scope ``seg_gather``)
— its self-time against the residual ``gather`` bucket is the measured
answer to whether the plan recovered the heavy-tail gather rate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)  # dgc_tpu is not an installed package
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_REPO, ".jax_cache"))

# the attribution library moved to tools/xplane_split.py (PR 11) so any
# profiler-window artifact — not just this driver's — gets the same
# category split; this driver keeps its run-one-attempt CLI contract
from tools.xplane_split import attribute_xspace  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=200_000)
    p.add_argument("--avg-degree", type=float, default=16.0)
    p.add_argument("--gen", choices=["fast", "rmat"], default="rmat")
    p.add_argument("--backend", choices=["ell-compact", "ell-bucketed", "ell"],
                   default="ell-compact")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logdir", type=str, default="/tmp/dgc_trace")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--xspace", type=str, default=None,
                   help="skip running; attribute an existing .xplane.pb")
    args = p.parse_args()

    if args.xspace:
        print(json.dumps(attribute_xspace(args.xspace, args.top)))
        return 0

    import jax

    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)

    gen = generate_rmat_graph if args.gen == "rmat" else generate_random_graph_fast
    arrays = gen(args.nodes, avg_degree=args.avg_degree, seed=args.seed)
    print(f"# graph V={arrays.num_vertices} E2={arrays.num_directed_edges} "
          f"maxdeg={arrays.max_degree}", file=sys.stderr)

    if args.backend == "ell-compact":
        from dgc_tpu.engine.compact import CompactFrontierEngine as Eng
    elif args.backend == "ell-bucketed":
        from dgc_tpu.engine.bucketed import BucketedELLEngine as Eng
    else:
        from dgc_tpu.engine.superstep import ELLEngine as Eng
    engine = Eng(arrays)
    k0 = arrays.max_degree + 1

    import time
    t0 = time.perf_counter()
    engine.attempt(k0)  # compile + warm outside the trace
    print(f"# warmup {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        t0 = time.perf_counter()
        res = engine.attempt(k0)
        jax.block_until_ready(res.colors if hasattr(res.colors, "device")
                              else res.supersteps)
        wall = time.perf_counter() - t0
    print(f"# traced attempt: {wall:.3f}s status={res.status}", file=sys.stderr)

    paths = sorted(glob.glob(os.path.join(args.logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        print("no .xplane.pb produced", file=sys.stderr)
        return 1
    out = attribute_xspace(paths[-1], args.top)
    out["attempt_wall_s"] = round(wall, 4)
    out["supersteps"] = int(res.supersteps)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Xplane-trace attribution for the staged kernels' gather-rate gap.

PERF.md's audits price sweeps in element gathers; the conversion to
seconds uses an *effective* ~45-55M lookups/s measured end-to-end — half
the raw 100-140M/s large-gather rate (``tools/rate_probe.py``). This tool
attributes the loss with a real profile instead of inference: it runs one
k-attempt under ``jax.profiler.trace`` and aggregates device-plane XLA op
time by category, so the question "is the lost time inside the gather
fusions themselves, between them (scheduling/cond gaps), or in
non-gather machinery?" gets a measured answer.

Usage — on the chip (the real use), run with the image's default env:

    python tools/trace_attempt.py [--nodes N] [--gen rmat|fast]

For CPU plumbing tests, scrub the sitecustomize path or the process dials
the TPU tunnel regardless of JAX_PLATFORMS (see .claude/skills/verify):

    PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/trace_attempt.py \
        [--nodes N] [--gen rmat|fast]
        [--backend ell-compact|ell-bucketed|ell] [--avg-degree D]
        [--seed S] [--logdir DIR] [--top N]

Prints one JSON object: total device time, a category breakdown
(segmented-gather / gather / scatter / while-overhead / collectives /
elementwise-fusion / copy / other), idle time (trace span − Σop), and the
top-N ops. ``segmented-gather`` is the fused O(1)-per-superstep gather of
the segmented plan (``ops.segmented_gather``, named scope ``seg_gather``)
— its self-time against the residual ``gather`` bucket is the measured
answer to whether the plan recovered the heavy-tail gather rate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)  # dgc_tpu is not an installed package
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_REPO, ".jax_cache"))

_CATEGORIES = (
    # order matters: first match wins
    # the segmented plan's fused gathers carry the ``seg_gather`` scope
    # (ops.segmented_gather.segmented_gather wraps THE gather in
    # jax.named_scope), so their self-time attributes separately from
    # residual small gathers — the on-chip measurement of the plan's rate
    # claim
    ("segmented-gather", re.compile(r"seg_gather", re.I)),
    ("gather", re.compile(r"gather|dynamic-slice(?!-update)|take", re.I)),
    ("scatter", re.compile(r"scatter|dynamic-update-slice", re.I)),
    ("collective", re.compile(r"all-gather|all-reduce|reduce-scatter|"
                              r"collective|permute", re.I)),
    ("copy", re.compile(r"copy|transpose|bitcast|reshape", re.I)),
    ("while-ctrl", re.compile(r"while|condition|tuple|parameter|select-n", re.I)),
    ("sort", re.compile(r"sort", re.I)),
    ("fusion-elementwise", re.compile(r"fusion", re.I)),
)


def _categorize(name: str) -> str:
    for cat, pat in _CATEGORIES:
        if pat.search(name):
            return cat
    return "other"


def _line_self_times(evts: list, into: dict) -> None:
    """Accumulate per-op SELF time (duration minus directly-nested child
    durations) for one trace line into ``into``.

    Trace lines nest events by time containment (a while op spans its body
    ops; on TPU the XLA Ops line nests control flow around fusions), so a
    plain sum double-counts every container. Stack-based interval nesting
    gives exact self-times without hierarchy metadata.
    """
    evts.sort(key=lambda e: (e[0], -e[1]))
    stack: list[list] = []  # [end, name, dur, child_sum]

    def close(upto: float) -> None:
        while stack and stack[-1][0] <= upto:
            end, name, dur, csum = stack.pop()
            into[name] = into.get(name, 0.0) + max(0.0, dur - csum)
            if stack:
                stack[-1][3] += dur

    for off, dur, name in evts:
        close(off)
        stack.append([off + dur, name, dur, 0.0])
    close(float("inf"))


def attribute_xspace(xspace_path: str, top: int = 20) -> dict:
    """Aggregate device-plane op SELF times from one ``.xplane.pb``."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(xspace_path, "rb") as f:
        xs.ParseFromString(f.read())

    # device planes: TPU (axon remote chip) or the host-CPU XLA plane when
    # run off-chip for plumbing tests
    planes = [p for p in xs.planes
              if "/device:" in p.name or "TPU" in p.name]
    if not planes:
        planes = [p for p in xs.planes if ":CPU" in p.name]
    # host/runtime scaffolding that shows up when the fallback picks a CPU
    # plane (python frames, PjRt/thunk wrappers, transfer/marker events) —
    # never real device ops. The module/step summary lines on TPU planes
    # span the whole execution and are skipped wholesale below.
    noise = re.compile(r"^\$|^PjRt|^Thunk|^PjitFunction|^XlaModule|"
                       r"^DevicePut|^np\.|^end: |^jit_|trace|__exit__")
    per_op: dict[str, float] = {}
    span_lo, span_hi = None, 0
    for plane in planes:
        meta = plane.event_metadata
        smeta = plane.stat_metadata
        lines = plane.lines

        def scoped_name(ev, name):
            """Named-scope attribution: the lowered instruction NAME never
            carries ``jax.named_scope`` labels — they live in the event's
            op_name/tf_op stat (and in the event metadata's display name
            on some backends). The segmented plan wraps its fused gather
            in ``seg_gather``; prefix the op so the category split sees
            it."""
            hay = [meta[ev.metadata_id].display_name]
            for st in ev.stats:
                sm = smeta.get(st.metadata_id)
                if sm is not None and sm.name in (
                        "tf_op", "op_name", "hlo_op", "long_name"):
                    hay.append(st.str_value
                               or (smeta.get(st.ref_value).name
                                   if st.ref_value else ""))
            if any(h and "seg_gather" in h for h in hay):
                return "seg_gather/" + name
            return name

        # TPU device planes carry an explicit "XLA Ops" line; when present
        # it is the only line with real per-op events
        op_lines = [l for l in lines if l.name == "XLA Ops"] or [
            l for l in lines if l.name not in ("XLA Modules", "Steps",
                                               "Framework Ops")]
        for line in op_lines:
            evts = []
            for ev in line.events:
                name = meta[ev.metadata_id].name
                if noise.search(name):
                    continue
                dur = ev.duration_ps / 1e12
                t0 = line.timestamp_ns * 1e-9 + ev.offset_ps / 1e12
                evts.append((t0, dur, scoped_name(ev, name)))
                span_lo = t0 if span_lo is None else min(span_lo, t0)
                span_hi = max(span_hi, t0 + dur)
            _line_self_times(evts, per_op)

    cats: dict[str, float] = {}
    for name, dur in per_op.items():
        cat = _categorize(name)
        cats[cat] = cats.get(cat, 0.0) + dur
    total = sum(per_op.values())
    span = (span_hi - span_lo) if span_lo is not None else 0.0
    top_ops = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    return {
        "planes": [p.name for p in planes],
        "device_op_time_s": round(total, 4),
        "trace_span_s": round(span, 4),
        "gap_time_s": round(max(0.0, span - total), 4),
        "categories_s": {k: round(v, 4)
                         for k, v in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"op": n, "s": round(d, 4)} for n, d in top_ops],
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=200_000)
    p.add_argument("--avg-degree", type=float, default=16.0)
    p.add_argument("--gen", choices=["fast", "rmat"], default="rmat")
    p.add_argument("--backend", choices=["ell-compact", "ell-bucketed", "ell"],
                   default="ell-compact")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logdir", type=str, default="/tmp/dgc_trace")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--xspace", type=str, default=None,
                   help="skip running; attribute an existing .xplane.pb")
    args = p.parse_args()

    if args.xspace:
        print(json.dumps(attribute_xspace(args.xspace, args.top)))
        return 0

    import jax

    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)

    gen = generate_rmat_graph if args.gen == "rmat" else generate_random_graph_fast
    arrays = gen(args.nodes, avg_degree=args.avg_degree, seed=args.seed)
    print(f"# graph V={arrays.num_vertices} E2={arrays.num_directed_edges} "
          f"maxdeg={arrays.max_degree}", file=sys.stderr)

    if args.backend == "ell-compact":
        from dgc_tpu.engine.compact import CompactFrontierEngine as Eng
    elif args.backend == "ell-bucketed":
        from dgc_tpu.engine.bucketed import BucketedELLEngine as Eng
    else:
        from dgc_tpu.engine.superstep import ELLEngine as Eng
    engine = Eng(arrays)
    k0 = arrays.max_degree + 1

    import time
    t0 = time.perf_counter()
    engine.attempt(k0)  # compile + warm outside the trace
    print(f"# warmup {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        t0 = time.perf_counter()
        res = engine.attempt(k0)
        jax.block_until_ready(res.colors if hasattr(res.colors, "device")
                              else res.supersteps)
        wall = time.perf_counter() - t0
    print(f"# traced attempt: {wall:.3f}s status={res.status}", file=sys.stderr)

    paths = sorted(glob.glob(os.path.join(args.logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        print("no .xplane.pb produced", file=sys.stderr)
        return 1
    out = attribute_xspace(paths[-1], args.top)
    out["attempt_wall_s"] = round(wall, 4)
    out["supersteps"] = int(res.supersteps)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env bash
# The repo's lint job, one entrypoint for every gate (pre-commit,
# evidence_suite.sh gate 0, CI):
#   1. dgc-lint --strict           — the five static passes vs the baseline
#   2. dgc-lint --fix --check      — no mechanical fix may be pending
#   3. ruff check (if installed)   — the generic layer (pyproject config)
# Fast (AST only, no kernels compiled) — seconds, not minutes.
set -u
cd "$(dirname "$0")/.."
rc=0

echo "=== dgc_lint --strict ===" >&2
python tools/dgc_lint.py --strict || rc=1

echo "=== dgc_lint --fix --check ===" >&2
python tools/dgc_lint.py --fix --check || {
  echo "ci_checks: mechanical fixes pending — run 'python tools/dgc_lint.py --fix'" >&2
  rc=1
}

if command -v ruff >/dev/null 2>&1; then
  echo "=== ruff check ===" >&2
  ruff check dgc_tpu tools bench.py || rc=1
else
  echo "ci_checks: ruff not installed — skipping (config in pyproject.toml)" >&2
fi

exit $rc

#!/usr/bin/env bash
# The repo's lint job, one entrypoint for every gate (pre-commit,
# evidence_suite.sh gate 0, CI):
#   1. dgc-lint --strict           — the five static passes vs the baseline
#   2. dgc-lint --fix --check      — no mechanical fix may be pending
#   3. ruff check (if installed)   — the generic layer (pyproject config)
#   4. retrospective-layer CPU smoke (PR 11, skip with DGC_TPU_CI_NO_SMOKE=1):
#      a tiny profile window -> tools/xplane_split.py -> a parsing
#      timing_crosscheck verdict, and a perf-ledger round trip with a
#      forced regression exiting nonzero.
#   5. netfront CPU soak smoke (PR 12, same DGC_TPU_CI_NO_SMOKE=1 skip):
#      tools/soak.py with a small client count over the real listener —
#      zero lost/dup results, quota 429s with retry context, graceful
#      drain — gated by tools/slo_check.py over the run manifest and
#      accreting a row into PERF_DB.jsonl via tools/perf_db.py.
#   6. chaos-serve smoke (crash-safe serve tier, same skip): 3 seeded
#      serve-point fault schedules + one SIGKILL-at-journal-offset
#      kill-resume cycle through tools/chaos_serve.py — recover or
#      structured abort at every serve fault point, zero acked-ticket
#      loss across the restart, colors bit-identical to fault-free.
#   7. chaos-mesh smoke (failure-domain plane, same skip): seeded
#      device-loss schedules under a forced 8-host-device mesh through
#      tools/chaos_mesh.py — survivor re-shard with colors bit-identical
#      to fault-free (serve tier AND the single-graph re-shard rung with
#      write-behind checkpoint resume), plus one kill-resume cycle on a
#      DEGRADED mesh with zero acked-ticket loss.
#   8. sharded serve-parity smoke (multi-device serve tier, same skip):
#      3 draws of the batched-vs-single bit-identity ensemble with the
#      lane axis sharded over a FORCED 8-host-device mesh
#      (XLA_FLAGS=--xla_force_host_platform_device_count=8) — colors,
#      supersteps, and attempt sequences byte-identical to the
#      single-graph sweep under sharding, seconds-scale.
#   9. fleet-telemetry smoke (telemetry plane, same skip): (a) a
#      synthesized multi-tenant journal with a crash-duplicate admit
#      must export schema-valid usage_rollup rows whose per-tenant sums
#      EXACTLY equal the journal's own totals (tools/usage_export.py
#      --check), and (b) an injected SLO violation — a failure burst
#      over warmed burn windows — must raise slo_burn AND dump the
#      flight recorder mid-incident; sub-second, pure CPU.
#  10. chaos-fleet smoke (replicated serve fleet, same skip): a real
#      2-replica fleet (SO_REUSEPORT one-port, per-replica journal
#      namespaces) through tools/chaos_fleet.py — one seeded
#      replica-subset SIGKILL at a merged-WAL offset with supervisor
#      respawn, a kill-all + cold fleet restart whose merge-scan
#      replays every acked ticket (colors bit-identical, zero dup ids
#      fleet-wide, usage conserved over the merged namespace WALs),
#      and the brownout tier contract (low tier 503-shed, premium
#      served); ~15s on CPU.
#  11. result-cache smoke (content-addressed result cache, same skip):
#      the duplicate-heavy four-leg soak A/B (tools/soak.py --cache-ab:
#      p50 served-latency speedup >= 5x at 60% duplicates AND
#      throughput overhead <= 2% at 0% duplicates, both SLO-gated by
#      the harness itself) plus the chaos_fleet cache arm
#      (--result-cache: duplicate traffic through kills + the
#      cold-cache probe proving the shared disk store survives
#      kill-all, colors byte-identical to the fault-free baseline,
#      cached deliveries present in the merged usage ledger).
#  12. speculation smoke (speculative minimal-k, same skip): a 3-draw
#      strict-decrement parity leg through SpeculativeMinimalKEngine —
#      colors, minimal k, and attempt sequences byte-identical to the
#      sequential single-graph sweep, with speculative attempts
#      actually seated AND the stopping rule's cancellation observed
#      (the window below the first failure dies, never leaks).
# Steps 1-3 are AST-only (seconds); steps 4-5 compile toy kernels on
# CPU (~1-2 min cold) — the only gates that prove the profiler and
# serving-over-the-network plumbing end-to-end before device time is
# spent.
set -u
cd "$(dirname "$0")/.."
rc=0

echo "=== dgc_lint --strict ===" >&2
python tools/dgc_lint.py --strict || rc=1

echo "=== dgc_lint --fix --check ===" >&2
python tools/dgc_lint.py --fix --check || {
  echo "ci_checks: mechanical fixes pending — run 'python tools/dgc_lint.py --fix'" >&2
  rc=1
}

if command -v ruff >/dev/null 2>&1; then
  echo "=== ruff check ===" >&2
  ruff check dgc_tpu tools bench.py || rc=1
else
  echo "ci_checks: ruff not installed — skipping (config in pyproject.toml)" >&2
fi

if [ "${DGC_TPU_CI_NO_SMOKE:-0}" != "1" ]; then
  echo "=== retrospective-layer CPU smoke ===" >&2
  SMOKE_DIR=$(mktemp -d)
  # profile window -> xplane split -> crosscheck verdict parses; the
  # xplane protobuf is optional on minimal images — absent skips, never
  # fails (the tier-1 tests carry the same skip)
  if python - <<'EOF' 2>/dev/null
from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401
EOF
  then
    if JAX_PLATFORMS=cpu timeout 300 python -m dgc_tpu.cli \
        --node-count 2000 --max-degree 12 --gen-method fast --seed 3 \
        --backend ell-compact \
        --output-coloring "$SMOKE_DIR/col.json" \
        --run-manifest "$SMOKE_DIR/man.json" --superstep-timing \
        --profile-window 1:99 --profile-logdir "$SMOKE_DIR/prof" \
        --flightrec-dir "$SMOKE_DIR" >/dev/null 2>&1 \
      && JAX_PLATFORMS=cpu timeout 120 python tools/xplane_split.py \
        "$SMOKE_DIR/man.json" --emit-runlog "$SMOKE_DIR/xc.jsonl" \
        2>/dev/null | python -c '
import json, sys
d = json.load(sys.stdin)
v = d["timing_crosscheck"]
assert v["verdict"] in ("ok", "divergent") and v["in_kernel_ms"] > 0, v
print("ci_checks: crosscheck verdict %s (coverage %s)"
      % (v["verdict"], v["coverage"]), file=sys.stderr)
' \
      && timeout 60 python tools/validate_runlog.py -q "$SMOKE_DIR/xc.jsonl"
    then
      echo "ci_checks: profile-window -> xplane_split smoke OK" >&2
    else
      echo "ci_checks: profile-window -> xplane_split smoke FAILED" >&2
      rc=1
    fi
  else
    echo "ci_checks: tsl xplane protobuf unavailable — skipping profiler smoke" >&2
  fi

  # perf-ledger round trip: seed a baseline, then a 2x slower record
  # must exit 1 (the regression tripwire contract)
  if echo '{"metric":"ci_smoke","value":1.0,"unit":"s","backend":"x","platform":"cpu"}' \
      | timeout 60 python tools/perf_db.py add --db "$SMOKE_DIR/perf.jsonl" >/dev/null 2>&1 \
    && ! echo '{"metric":"ci_smoke","value":2.0,"unit":"s","backend":"x","platform":"cpu"}' \
      | timeout 60 python tools/perf_db.py add --db "$SMOKE_DIR/perf.jsonl" >/dev/null 2>&1 \
    && timeout 60 python tools/perf_db.py report --db "$SMOKE_DIR/perf.jsonl" >/dev/null
  then
    echo "ci_checks: perf_db round-trip smoke OK" >&2
  else
    echo "ci_checks: perf_db round-trip smoke FAILED" >&2
    rc=1
  fi
  # netfront soak smoke (PR 12): a small-count run of the many-client
  # harness — the soak's own invariants (zero lost/dup, quota 429s,
  # graceful drain) exit nonzero, then the SLO gate runs over the
  # manifest and the record accretes into the perf ledger. Thresholds
  # are structural (failure rate + a generous p95): the gate proves the
  # pipeline, PERF.md holds the measured numbers.
  cat > "$SMOKE_DIR/slo_soak.json" <<'EOF'
{"service_ms": {"p95": 60000}, "failure_rate_max": 0.0}
EOF
  if JAX_PLATFORMS=cpu timeout 300 python tools/soak.py \
      --clients 32 --requests-per-client 2 --greedy-clients 4 \
      --nodes 120 --degree 6 \
      --log-json "$SMOKE_DIR/soak.jsonl" \
      --run-manifest "$SMOKE_DIR/soak_man.json" \
      > "$SMOKE_DIR/soak_record.json" \
    && timeout 60 python tools/validate_runlog.py -q "$SMOKE_DIR/soak.jsonl" \
    && timeout 60 python tools/slo_check.py "$SMOKE_DIR/soak_man.json" \
      --thresholds "$SMOKE_DIR/slo_soak.json" \
    && timeout 60 python tools/perf_db.py add --db PERF_DB.jsonl \
      --threshold 0.5 --record "$SMOKE_DIR/soak_record.json" >/dev/null
  then
    echo "ci_checks: netfront soak smoke OK ($(cat "$SMOKE_DIR/soak_record.json" | python -c 'import json,sys; r=json.load(sys.stdin); print(r["requests"], "req,", r["value"], r["unit"])'))" >&2
  else
    echo "ci_checks: netfront soak smoke FAILED" >&2
    rc=1
  fi
  # chaos-serve smoke (crash-safe serve tier): seeded schedules over
  # every serve fault point + one kill-resume cycle over the durable
  # ticket journal; the harness's own invariants (zero acked loss, no
  # dup ids, bit-identical replay colors, schema-valid logs) exit
  # nonzero, and the report is structurally validated on top
  if JAX_PLATFORMS=cpu timeout 560 python tools/chaos_serve.py \
      --schedules 3 --kills 1 --clients 3 --requests-per-client 2 \
      --nodes 400 --degree 5 --deadline 240 \
      --report "$SMOKE_DIR/chaos_serve.json" \
      > "$SMOKE_DIR/chaos_serve_summary.json" \
    && python - "$SMOKE_DIR/chaos_serve.json" <<'EOF'
import json, sys
sys.path.insert(0, ".")
from tools.chaos_serve import validate_chaos_serve_report
doc = json.load(open(sys.argv[1]))
problems = validate_chaos_serve_report(doc)
assert not problems, problems
assert doc["summary"]["failed"] == 0, doc["summary"]
kr = doc.get("kill_resume")
assert kr and kr["outcome"] == "ok" and kr["kills"] >= 1, kr
assert kr.get("usage_conservation") == "ok", kr
print("ci_checks: chaos-serve %d schedule(s) + kill-resume ok "
      "(usage conserved, %d cross-incarnation trace(s))"
      % (len(doc["schedules"]), kr.get("cross_incarnation_traces", 0)),
      file=sys.stderr)
EOF
  then
    echo "ci_checks: chaos-serve smoke OK" >&2
  else
    echo "ci_checks: chaos-serve smoke FAILED" >&2
    rc=1
  fi
  # chaos-mesh smoke (failure-domain plane): 3 seeded device-loss
  # schedules over the serve mesh points + the 3 single-graph re-shard
  # variants (mesh-build / mid-sweep checkpoint resume / double loss)
  # + 1 kill-resume cycle on a DEGRADED mesh — the harness's own
  # invariants (recovery-or-structured-abort, zero acked loss,
  # bit-identical colors, schema-valid logs) exit nonzero, and the
  # report is structurally validated on top
  if timeout 560 python tools/chaos_mesh.py \
      --schedules 3 --sweeps 3 --kill-resume 1 \
      --clients 2 --requests-per-client 2 --deadline 240 \
      --report "$SMOKE_DIR/chaos_mesh.json" \
      > "$SMOKE_DIR/chaos_mesh_summary.json" \
    && python - "$SMOKE_DIR/chaos_mesh.json" <<'EOF'
import json, sys
sys.path.insert(0, ".")
from tools.chaos_mesh import validate_chaos_mesh_report
doc = json.load(open(sys.argv[1]))
problems = validate_chaos_mesh_report(doc)
assert not problems, problems
assert doc["summary"]["failed"] == 0, doc["summary"]
kr = doc.get("kill_resume")
assert kr and kr["outcome"] == "ok" and kr["kills"] >= 1, kr
print("ci_checks: chaos-mesh %d schedule(s) + %d sweep(s) + degraded "
      "kill-resume ok" % (len(doc["schedules"]), len(doc["sweeps"])),
      file=sys.stderr)
EOF
  then
    echo "ci_checks: chaos-mesh smoke OK" >&2
  else
    echo "ci_checks: chaos-mesh smoke FAILED" >&2
    rc=1
  fi
  # sharded serve-parity smoke (multi-device serve tier): a 3-draw leg
  # of the bit-identity ensemble with --mesh-devices over a forced
  # 8-host-device mesh — the cheapest end-to-end proof that the sharded
  # compile path (Mesh + NamedSharding over the lane axis) stays
  # byte-identical to the single-device scheduler; the committed
  # 12-draw artifact is tools/serve_parity.jsonl
  if PYTHONPATH=. JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      timeout 560 python tools/bit_identity_ensemble.py --serve \
      --draws 3 --serve-slice-steps 2 --serve-mesh-devices 8 \
      --out "$SMOKE_DIR/serve_parity_mesh.jsonl" >/dev/null 2>&1 \
    && python - "$SMOKE_DIR/serve_parity_mesh.jsonl" <<'EOF'
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1])]
summary = lines[-1]
assert summary["mismatches"] == 0, summary
assert summary["mesh_devices"] == 8, summary
print("ci_checks: sharded serve parity %d draw(s), 0 mismatches"
      % summary["draws"], file=sys.stderr)
EOF
  then
    echo "ci_checks: sharded serve-parity smoke OK" >&2
  else
    echo "ci_checks: sharded serve-parity smoke FAILED" >&2
    rc=1
  fi
  # fleet-telemetry smoke (telemetry plane): (a) usage-export
  # conservation — a synthesized journal with a crash-duplicate admit
  # exported through the CLI's --check gate (per-tenant sums must
  # EXACTLY equal the journal's own totals; the artifact must be a
  # schema-valid run log); (b) injected SLO violation — a failure
  # burst over warmed fast+slow burn windows must raise slo_burn,
  # dump the flight recorder mid-incident, and leave a schema-valid
  # event stream
  if timeout 120 python - "$SMOKE_DIR" <<'EOF'
import glob, json, sys, time
sys.path.insert(0, ".")
sys.path.insert(0, "tools")
import slo_check
from dgc_tpu.obs import FlightRecorder, MetricsRegistry, RunLogger
from dgc_tpu.obs.timeseries import BurnRateEvaluator, TimeseriesSampler
from dgc_tpu.serve.netfront import TicketJournal
from tools.usage_export import main as export_main
from tools.validate_runlog import validate_file

smoke = sys.argv[1]

# (a) journal -> usage_rollup artifact -> conservation gate
spec = {"node_count": 24, "max_degree": 3, "seed": 5, "gen_method": "fast"}
j = TicketJournal(smoke + "/usage_journal")
j.append("admitted", "t00000000", tenant="acme", payload=dict(spec))
# crash-window duplicate admit: metered once or conservation breaks
j.append("admitted", "t00000000", tenant="acme", payload=dict(spec))
j.append("attempt", "t00000000", durable=False, k=3, status="SUCCESS",
         supersteps=5)
j.append("delivered", "t00000000", durable=False,
         result={"status": "ok", "queue_ms": 2.0, "service_ms": 8.0})
j.append("admitted", "t00000001", tenant="beta", payload=dict(spec))
j.append("aborted", "t00000001", reason="queue_full")
j.close()
out = smoke + "/usage.jsonl"
rc = export_main([smoke + "/usage_journal", "-o", out, "--check"])
assert rc == 0, "usage_export --check exited %d" % rc
rows = [json.loads(ln) for ln in open(out) if ln.strip()]
assert {r["tenant"] for r in rows} == {"acme", "beta"}, rows
assert all(r["event"] == "usage_rollup" for r in rows), rows
assert validate_file(out) == [], validate_file(out)
print("ci_checks: usage-export conservation ok (%d tenant row(s))"
      % len(rows), file=sys.stderr)

# (b) injected SLO violation -> slo_burn + flight-recorder dump
registry = MetricsRegistry()
log = smoke + "/burn.jsonl"
logger = RunLogger(jsonl_path=log, echo=False)
recorder = FlightRecorder(capacity=32, registry=registry)
logger.add_sink(recorder)
hooks = slo_check.ViolationHooks(recorder=recorder, dump_dir=smoke,
                                 logger=logger)
sampler = TimeseriesSampler(registry, interval_s=9.0, capacity=16)
ev = BurnRateEvaluator(sampler, {"failure_rate_max": 0.1},
                       fast_window_s=0.1, slow_window_s=0.1,
                       hooks=hooks, logger=logger, registry=registry)
ok = registry.counter("dgc_serve_requests_total", "reqs", status="ok")
err = registry.counter("dgc_serve_requests_total", "reqs", status="error")
ok.inc()
sampler.sample_once()
# >= half-span coverage but still inside the 0.1s windows
time.sleep(0.06)
for _ in range(9):
    err.inc()
fired = ev.evaluate(sampler.sample_once())
assert [f["objective"] for f in fired] == ["failure_rate"], fired
logger.close()
recs = [json.loads(ln) for ln in open(log) if ln.strip()]
burns = [r for r in recs if r.get("event") == "slo_burn"]
assert len(burns) == 1 and burns[0]["burn"] >= 1.0, burns
dumps = [r for r in recs if r.get("event") == "flightrec_dump"]
assert dumps and dumps[0]["reason"] == "slo_violation", dumps
assert glob.glob(smoke + "/flightrec_*.jsonl"), "no flight-recorder dump"
assert validate_file(log) == [], validate_file(log)
print("ci_checks: injected SLO violation -> slo_burn + flightrec dump ok",
      file=sys.stderr)
EOF
  then
    echo "ci_checks: fleet-telemetry smoke OK" >&2
  else
    echo "ci_checks: fleet-telemetry smoke FAILED" >&2
    rc=1
  fi
  # chaos-fleet smoke (replicated serve fleet): 2 replicas on one
  # SO_REUSEPORT port, 1 seeded replica-subset kill at a merged-WAL
  # offset + the kill-all cold restart + the brownout tier contract —
  # the harness's own invariants (zero acked loss, zero dup ids
  # FLEET-WIDE, bit-identical replay colors, usage conservation over
  # the merged namespace WALs) exit nonzero, and the report is
  # structurally validated on top
  if JAX_PLATFORMS=cpu timeout 560 python tools/chaos_fleet.py \
      --replicas 2 --kills 1 --clients 2 --requests-per-client 1 \
      --nodes 120 --degree 6 --deadline 240 \
      --report "$SMOKE_DIR/chaos_fleet.json" \
      > "$SMOKE_DIR/chaos_fleet_summary.json" \
    && python - "$SMOKE_DIR/chaos_fleet.json" <<'EOF'
import json, sys
sys.path.insert(0, ".")
from tools.chaos_fleet import validate_chaos_fleet_report
doc = json.load(open(sys.argv[1]))
problems = validate_chaos_fleet_report(doc)
assert not problems, problems
assert doc["summary"]["failed"] == 0, doc["summary"]
kr = doc["kill_resume"]
assert kr["outcome"] == "ok" and kr["kills"] >= 1, kr
cold = doc["cold_restart"]
assert cold["outcome"] == "ok", cold
assert cold["usage_conservation"] == "ok", cold
bo = doc["brownout"]
assert bo["outcome"] == "ok" and bo["shed"] >= 1, bo
print("ci_checks: chaos-fleet kill-resume + cold restart ok "
      "(%d namespace(s) merged, %d ticket(s) stable, brownout shed %d)"
      % (cold["namespaces"], cold["tickets_stable"], bo["shed"]),
      file=sys.stderr)
EOF
  then
    echo "ci_checks: chaos-fleet smoke OK" >&2
  else
    echo "ci_checks: chaos-fleet smoke FAILED" >&2
    rc=1
  fi
  # result-cache smoke (content-addressed result cache + coalescing):
  # the four-leg soak A/B gates the >=5x speedup, then the chaos_fleet
  # cache arm proves cached results survive kills AND kill-all cold
  # restart byte-identical to the fault-free baseline (the cold-cache
  # probe hits the shared disk store through empty post-restart LRUs).
  # The overhead gate is structural here (<=15%): the smoke's 0.3s
  # walls on a 1-core host flap ±5% on scheduler noise alone — the
  # measured <=2% row comes from the full-size A/B (PERF.md
  # "Content-addressed result cache").
  if JAX_PLATFORMS=cpu timeout 560 python tools/soak.py \
      --cache-ab --ab-trials 3 --duplicate-pct 60 \
      --clients 6 --requests-per-client 3 --nodes 40 --degree 4 \
      --result-cache 128 --cache-overhead-slo 15 \
      > "$SMOKE_DIR/cache_ab.jsonl" \
    && JAX_PLATFORMS=cpu timeout 560 python tools/chaos_fleet.py \
      --replicas 2 --kills 1 --clients 4 --requests-per-client 2 \
      --nodes 120 --degree 6 --deadline 240 --result-cache 64 \
      --skip-brownout \
      --report "$SMOKE_DIR/chaos_fleet_cache.json" \
      > "$SMOKE_DIR/chaos_fleet_cache_summary.json" \
    && python - "$SMOKE_DIR/cache_ab.jsonl" "$SMOKE_DIR/chaos_fleet_cache.json" <<'EOF'
import json, sys
recs = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
by = {r["metric"].split("_c6_")[0]: r for r in recs}
sp = by["soak_cache_speedup"]
ov = by["soak_cache_overhead"]
assert sp["soak_ok"] and sp["value"] >= sp["slo_speedup_x_min"], sp
assert ov["soak_ok"] and ov["value"] <= ov["slo_overhead_pct_max"], ov
doc = json.load(open(sys.argv[2]))
cold = doc["cold_restart"]
assert doc["summary"]["failed"] == 0, doc["summary"]
assert cold["outcome"] == "ok", cold
assert cold["cache_probes_ok"] == 2, cold
assert cold["cached_deliveries"] > 0, cold
print("ci_checks: result-cache A/B %sx speedup / %s%% overhead, "
      "chaos cache arm ok (%d cached deliveries, %d cold probes)"
      % (sp["value"], ov["value"], cold["cached_deliveries"],
         cold["cache_probes_ok"]), file=sys.stderr)
EOF
  then
    echo "ci_checks: result-cache smoke OK" >&2
  else
    echo "ci_checks: result-cache smoke FAILED" >&2
    rc=1
  fi
  # speculation smoke (speculative minimal-k): 3-draw strict-decrement
  # parity through the speculative engine + the cancellation contract
  if JAX_PLATFORMS=cpu timeout 300 python - <<'EOF'
import sys
sys.path.insert(0, ".")
import numpy as np
from dgc_tpu.engine.compact import CompactFrontierEngine
from dgc_tpu.engine.minimal_k import (find_minimal_coloring,
                                      make_reducer, make_validator)
from dgc_tpu.models.generators import generate_random_graph_fast
from dgc_tpu.serve.engine import BatchScheduler
from dgc_tpu.serve.shape_classes import DEFAULT_LADDER, pad_member
from dgc_tpu.serve.speculate import SpeculativeMinimalKEngine

events = []
sched = BatchScheduler(batch_max=4, window_s=0.0, slice_steps=2,
                       on_event=lambda k, r: events.append((k, r))).start()
try:
    for seed in (1, 2, 3):
        g = generate_random_graph_fast(300 + 60 * seed, avg_degree=5,
                                       seed=seed)
        want_attempts, got_attempts = [], []
        want = find_minimal_coloring(
            CompactFrontierEngine(g), initial_k=g.max_degree + 1,
            strict_decrement=True, validate=make_validator(g),
            on_attempt=lambda r, v: want_attempts.append(
                (int(r.k), r.status.name, int(r.supersteps))),
            post_reduce=make_reducer(g))
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        eng = SpeculativeMinimalKEngine(pad_member(g, cls), sched, depth=2)
        try:
            got = find_minimal_coloring(
                eng, initial_k=eng.member.k0, strict_decrement=True,
                validate=make_validator(g),
                on_attempt=lambda r, v: got_attempts.append(
                    (int(r.k), r.status.name, int(r.supersteps))),
                post_reduce=make_reducer(g))
        finally:
            eng.close()
        assert got.minimal_colors == want.minimal_colors
        assert np.array_equal(got.colors, want.colors)
        assert got_attempts == want_attempts, (got_attempts, want_attempts)
    stats = sched.stats_snapshot()
finally:
    sched.stop()
assert stats["spec_seated"] > 0, stats
assert stats["spec_wins"] > 0, stats
# the stopping rule cancels the window below the first failure
assert stats["spec_cancelled"] > 0, stats
kinds = {k for k, _ in events}
assert {"spec_seated", "spec_win", "spec_cancelled"} <= kinds, kinds
print("ci_checks: speculation parity 3 draw(s), %d seated / %d win(s) "
      "/ %d cancelled" % (stats["spec_seated"], stats["spec_wins"],
                          stats["spec_cancelled"]), file=sys.stderr)
EOF
  then
    echo "ci_checks: speculation smoke OK" >&2
  else
    echo "ci_checks: speculation smoke FAILED" >&2
    rc=1
  fi
  # mega-dispatch smoke (device-resident minimal-k): 3-draw parity of
  # the blocked driver (attempts_per_dispatch=3) against the sequential
  # sweep in both strict and jump modes, plus the dispatch-count
  # amortization observable
  if JAX_PLATFORMS=cpu timeout 300 python - <<'EOF'
import sys
sys.path.insert(0, ".")
import numpy as np
from dgc_tpu.engine.compact import CompactFrontierEngine
from dgc_tpu.engine.minimal_k import (find_minimal_coloring,
                                      make_reducer, make_validator)
from dgc_tpu.models.generators import generate_random_graph_fast
from dgc_tpu.obs import MetricsRegistry
from dgc_tpu.obs.instrument import ObservedEngine

d_seq = d_blk = 0
for seed in (1, 2, 3):
    g = generate_random_graph_fast(300 + 60 * seed, avg_degree=5,
                                   seed=seed)
    for strict in (True, False):
        runs = []
        for attempts in (1, 3):
            reg = MetricsRegistry()
            eng = ObservedEngine(CompactFrontierEngine(g), registry=reg,
                                 record_trajectory=False)
            attempt_log = []
            res = find_minimal_coloring(
                eng, initial_k=g.max_degree + 1, strict_decrement=strict,
                validate=make_validator(g),
                on_attempt=lambda r, v: attempt_log.append(
                    (int(r.k), r.status.name, int(r.supersteps),
                     int(r.colors_used))),
                post_reduce=make_reducer(g),
                attempts_per_dispatch=attempts)
            disp = int(reg.counter("dgc_device_dispatches_total").value)
            runs.append((res, attempt_log, disp))
        (want, want_at, ds), (got, got_at, db) = runs
        assert got.minimal_colors == want.minimal_colors
        assert np.array_equal(got.colors, want.colors)
        assert got_at == want_at, (got_at, want_at)
        assert db <= ds, (db, ds)
        if strict:
            d_seq, d_blk = d_seq + ds, d_blk + db
# 3-attempt blocks must amortize the strict chains' dispatch count
assert d_blk < d_seq, (d_blk, d_seq)
print("ci_checks: mega-dispatch parity 3 draw(s) x {strict,jump}, "
      "%d -> %d strict dispatches" % (d_seq, d_blk), file=sys.stderr)
EOF
  then
    echo "ci_checks: mega-dispatch smoke OK" >&2
  else
    echo "ci_checks: mega-dispatch smoke FAILED" >&2
    rc=1
  fi
  rm -rf "$SMOKE_DIR"
fi

exit $rc

#!/usr/bin/env python
"""dgc-lint: the repo's static-analysis gate (``dgc_tpu.analysis``).

Runs the four AST passes (kernel staging KS*, carry/layout LY*, event
schema SC*, lock discipline LK*) over the package and compares the
findings against the committed baseline of accepted exceptions.

Usage:
  python tools/dgc_lint.py                 # report all findings
  python tools/dgc_lint.py --strict        # exit 1 on any non-baselined
  python tools/dgc_lint.py --passes locks  # one pass only
  python tools/dgc_lint.py --write-baseline  # accept current findings

Exit codes: 0 clean (or all findings baselined), 1 non-baselined
findings under ``--strict``, 2 usage/load error.

The baseline (``tools/dgc_lint_baseline.json``) keys findings by
``(rule, file, detail)`` — no line numbers, so unrelated edits never
churn it. A stale baseline entry (accepted finding that no longer
fires) is reported so the baseline shrinks monotonically; under
``--strict`` staleness is a warning, not a failure.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.analysis import (PASSES, load_baseline, run_passes,  # noqa: E402
                              split_baseline, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="repo root (default: this script's parent repo)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/"
                         "dgc_lint_baseline.json under the root)")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of {','.join(PASSES)}")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any non-baselined finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parent.parent
    if not (root / "dgc_tpu").is_dir():
        print(f"dgc_lint: no dgc_tpu package under {root}",
              file=sys.stderr)
        return 2
    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = [p for p in passes if p not in PASSES]
    if unknown:
        print(f"dgc_lint: unknown pass(es) {unknown}; "
              f"have {list(PASSES)}", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else \
        root / "tools" / "dgc_lint_baseline.json"

    try:
        findings = run_passes(root, passes)
    except (OSError, SyntaxError) as e:
        print(f"dgc_lint: cannot analyze: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"dgc_lint: wrote {len(findings)} accepted finding(s) to "
              f"{baseline_path}")
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except (OSError, ValueError, KeyError) as e:
        print(f"dgc_lint: bad baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2
    new, accepted, stale = split_baseline(findings, baseline)

    for f in new:
        print(f)
    if accepted:
        print(f"dgc_lint: {len(accepted)} baselined finding(s) suppressed")
    for rule, file, detail in stale:
        print(f"dgc_lint: stale baseline entry {rule} {file}: {detail} "
              f"(no longer fires — remove it)", file=sys.stderr)
    npass = len(passes)
    print(f"dgc_lint: {npass} pass(es), {len(findings)} finding(s), "
          f"{len(new)} new")
    if new and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

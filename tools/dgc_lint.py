#!/usr/bin/env python
"""dgc-lint: the repo's static-analysis gate (``dgc_tpu.analysis``).

Runs the five passes (kernel staging KS*, carry/layout LY*, event
schema SC*, lock discipline LK* incl. the cross-object points-to rule,
transfer/donation discipline TR*) over the package and compares the
findings against the committed baseline of accepted exceptions.

Usage:
  python tools/dgc_lint.py                 # report all findings
  python tools/dgc_lint.py --strict        # exit 1 on any non-baselined
  python tools/dgc_lint.py --passes locks  # one pass only
  python tools/dgc_lint.py --write-baseline  # accept current findings
  python tools/dgc_lint.py --fix           # apply mechanical fixes
  python tools/dgc_lint.py --fix --check   # CI: exit 1 iff a fix pends

Exit codes: 0 clean (or all findings baselined / no fixes pending),
1 non-baselined findings under ``--strict`` or pending fixes under
``--fix --check``, 2 usage/load error.

The baseline (``tools/dgc_lint_baseline.json``) keys findings by
``(rule, file, detail)`` — no line numbers, so unrelated edits never
churn it. Stale baseline entries (accepted findings that no longer
fire) are reported on every run and PRUNED by ``--write-baseline``
(the written file holds exactly the current findings). Per-line
waivers (``# dgc-lint: ok RULE``) that suppress nothing are warned
about — dead waivers rot like stale baseline entries.

``--fix`` applies the two mechanically-derivable fixes (``dgc_tpu
.analysis.fixer``): inserting ``# guarded-by:`` annotations where
every access already holds one consistent lock, and rewriting bare
integer carry indices to ``dgc_tpu.layout`` named slots. Both are
idempotent and line-local; ``--fix --check`` plans without writing.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.analysis import (LAYOUT_FILES, LOCK_FILES,  # noqa: E402
                              PASSES, load_baseline, run_report,
                              split_baseline, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="repo root (default: this script's parent repo)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/"
                         "dgc_lint_baseline.json under the root)")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of {','.join(PASSES)}")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any non-baselined finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "(prunes stale entries)")
    ap.add_argument("--fix", action="store_true",
                    help="apply mechanical fixes (guarded-by insertion, "
                         "named-slot rewrites)")
    ap.add_argument("--check", action="store_true",
                    help="with --fix: plan only, exit 1 iff any fix "
                         "would be applied")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parent.parent
    if not (root / "dgc_tpu").is_dir():
        print(f"dgc_lint: no dgc_tpu package under {root}",
              file=sys.stderr)
        return 2
    if args.check and not args.fix:
        print("dgc_lint: --check requires --fix", file=sys.stderr)
        return 2
    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = [p for p in passes if p not in PASSES]
    if unknown:
        print(f"dgc_lint: unknown pass(es) {unknown}; "
              f"have {list(PASSES)}", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else \
        root / "tools" / "dgc_lint_baseline.json"

    if args.fix:
        from dgc_tpu.analysis.fixer import apply_fixes, plan_fixes

        try:
            fixes = plan_fixes(root, LOCK_FILES, LAYOUT_FILES)
        except (OSError, SyntaxError) as e:
            print(f"dgc_lint: cannot plan fixes: {e}", file=sys.stderr)
            return 2
        for fix in fixes:
            print(fix)
        if args.check:
            print(f"dgc_lint: {len(fixes)} fix(es) pending")
            return 1 if fixes else 0
        applied = apply_fixes(root, fixes)
        print(f"dgc_lint: applied {applied} fix(es)")
        return 0

    try:
        report = run_report(root, passes)
    except (OSError, SyntaxError) as e:
        print(f"dgc_lint: cannot analyze: {e}", file=sys.stderr)
        return 2
    findings = report.findings
    for rel, line, rule in report.unused_waivers:
        print(f"dgc_lint: waiver '{rule}' at {rel}:{line} matches no "
              f"finding (dead waiver — remove it)", file=sys.stderr)

    if args.write_baseline:
        try:
            old = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError):
            old = set()
        keys = {f.key() for f in findings}
        pruned = len(old - keys)
        write_baseline(baseline_path, findings)
        print(f"dgc_lint: wrote {len(keys)} accepted finding(s) to "
              f"{baseline_path}"
              + (f" (pruned {pruned} stale)" if pruned else ""))
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except (OSError, ValueError, KeyError) as e:
        print(f"dgc_lint: bad baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2
    new, accepted, stale = split_baseline(findings, baseline)

    for f in new:
        print(f)
    if accepted:
        print(f"dgc_lint: {len(accepted)} baselined finding(s) suppressed")
    for rule, file, detail in stale:
        print(f"dgc_lint: stale baseline entry {rule} {file}: {detail} "
              f"(no longer fires — remove it or --write-baseline)",
              file=sys.stderr)
    npass = len(passes)
    print(f"dgc_lint: {npass} pass(es), {len(findings)} finding(s), "
          f"{len(new)} new")
    if new and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# The round's full TPU evidence capture, one command:
#   1. the benchmark battery (tools/bench_suite.sh — PERF.md's tables),
#   2. the gather-rate probe (tools/rate_probe.py),
#   3. an xplane trace attribution of the 200k-RMAT attempt
#      (tools/trace_attempt.py — the rate-gap decomposition),
#   4. a cold-compile measurement of the unified heavy-tail pipeline at
#      1M-RMAT (the round-3 lever's first real-TPU number).
# Run via tools/bench_when_up.sh to fire unattended on tunnel recovery:
#   bash tools/bench_when_up.sh   # (watcher delegates here when EVIDENCE=1)
# or directly once the tunnel is up:
#   bash tools/evidence_suite.sh [outfile]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-PERF_RUNS.jsonl}"

bash tools/bench_suite.sh "$OUT"
battery_rc=$?

# the probes are best-effort: a battery abort (rc 2) means the tunnel is
# gone again — skip them rather than hang
if [ "$battery_rc" -ne 2 ]; then
  echo "=== rate probe ===" | tee -a /dev/stderr >/dev/null
  timeout 1800 python tools/rate_probe.py 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> rate_probe_r4.jsonl || true

  echo "=== trace attribution (200k RMAT attempt) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/trace_attempt.py --nodes 200000 --gen rmat \
    --logdir /tmp/dgc_trace_r4 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> trace_attr_r4.json || true

  echo "=== cold compile, unified pipeline 1M-RMAT ===" | tee -a /dev/stderr >/dev/null
  # fresh cache dir = genuinely cold compile; report warmup line only
  JAX_COMPILATION_CACHE_DIR=$(mktemp -d) timeout 3600 \
    python bench.py --gen rmat --nodes 1000000 --include-compile 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
fi

echo "evidence capture done (battery rc=$battery_rc)" >&2
exit "$battery_rc"

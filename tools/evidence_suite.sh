#!/usr/bin/env bash
# The round's full TPU evidence capture, one command:
#   1. the benchmark battery (tools/bench_suite.sh — PERF.md's tables),
#   2. the gather-rate probe (tools/rate_probe.py),
#   3. an xplane trace attribution of the 200k-RMAT attempt
#      (tools/trace_attempt.py — the rate-gap decomposition),
#   4. a cold-compile measurement of the unified heavy-tail pipeline at
#      1M-RMAT (the round-3 lever's first real-TPU number).
# tools/bench_when_up.sh delegates here BY DEFAULT on tunnel recovery
# (set DGC_TPU_BATTERY_ONLY=1 there for just the battery); or run
# directly once the tunnel is up:
#   bash tools/evidence_suite.sh [outfile]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-PERF_RUNS.jsonl}"

# gate 0 — static analysis: the structural invariants every evidence run
# leans on (kernel staging, carry/traj layout, event schema, lock
# discipline incl. the cross-object points-to rule, transfer/donation
# discipline) must hold BEFORE burning device time. One entrypoint
# (tools/ci_checks.sh) shared with the pre-commit hook and the tier-1
# test (tests/test_dgc_lint.py): dgc-lint --strict, --fix --check, and
# ruff where installed.
echo "=== lint gate (tools/ci_checks.sh) ===" >&2
if ! bash tools/ci_checks.sh; then
  echo "evidence_suite: lint gate failed — fix, apply --fix, or baseline before capturing evidence" >&2
  exit 3
fi

bash tools/bench_suite.sh "$OUT"
battery_rc=$?

# the probes are best-effort: a battery abort (rc 2) means the tunnel is
# gone again — skip them rather than hang
if [ "$battery_rc" -ne 2 ]; then
  echo "=== rate probe ===" | tee -a /dev/stderr >/dev/null
  timeout 1800 python tools/rate_probe.py 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> rate_probe_r4.jsonl || true

  echo "=== trace attribution (200k RMAT attempt) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/trace_attempt.py --nodes 200000 --gen rmat \
    --logdir /tmp/dgc_trace_r4 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> trace_attr_r4.jsonl || true

  # segmented-gather plan rate measurement (PR 3, queued while the tunnel
  # was down): the rate_probe run above already carries the A/B pair
  # (loop_6range_chain vs loop_segmented_1flat — same volume, 6 dependent
  # range gathers vs ONE fused gather); this trace attributes the staged
  # kernel's seg_gather self-time end-to-end on the 1M-RMAT heavy tail.
  # Expected per PERF.md "Segmented-gather plan": effective rate recovers
  # from ~16.6M lookups/s toward the 100-140M/s primitive.
  echo "=== segmented-plan trace (1M RMAT attempt) ===" | tee -a /dev/stderr >/dev/null
  timeout 5400 python tools/trace_attempt.py --nodes 1000000 --gen rmat \
    --logdir /tmp/dgc_trace_seg 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> trace_attr_seg.jsonl || true

  # tuned-vs-static A/B (schedule auto-tuner, dgc_tpu.tune): same graph,
  # shipped ladder vs the committed tuned config (tools/tuned_configs/,
  # emitted chip-free by `python -m dgc_tpu.tune` — regenerate with
  # --out if the generators change). The tuner's modeled wins
  # (PERF.md "Auto-tuned schedules": −10.9% gather volume at 200k-RMAT,
  # −9.2% at 1M-RMAT) land here as measured sweep wall-clock deltas;
  # results are bit-identical by construction, so any color/superstep
  # drift in these rows is a bug, not a tuning effect.
  echo "=== tuned-vs-static A/B (200k RMAT) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python bench.py --gen rmat --nodes 200000 --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
  timeout 3600 python bench.py --gen rmat --nodes 200000 \
    --tuned-config tools/tuned_configs/rmat_200k.json --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # serve-throughput A/B (PR 5/6, dgc_tpu.serve): graphs/s of the batched
  # vmap'd front-end vs sequential single-graph sweeps of the same 20k
  # graphs, batch 1/8/32, CONTINUOUS (lane recycling, PR 6) vs SYNC
  # (batch-complete, PR 5) measured over the same graphs — the
  # continuous-vs-batch-synchronous A/B. The CPU rows (PERF.md
  # "Continuous batching") are bandwidth-bound on one core; the TPU
  # questions are (a) whether lane-parallel batching opens the
  # batch-8/batch-1 ratio (the ~65 ms/dispatch amortization) and
  # (b) how much lane recycling beats the straggler-synced batch-32 when
  # lanes are PARALLEL hardware, not serial work — there every idle
  # straggler lane is a wasted parallel lane, exactly what recycling
  # reclaims. Slice size is the auto policy (serve.batched
  # .auto_slice_steps prices ~65 ms dispatch overhead on-chip). Results
  # are color-parity-checked in-run (parity_ok in the JSON line), and
  # the monotone_curve flag records the no-cliff acceptance over
  # multi-lane widths.
  # 64-graph stream (2× the widest pool) so every width gets refill
  # overlap — a burst equal to the pool width under-measures wide pools
  # (the ramp has nothing to overlap into; PERF.md methodology note)
  echo "=== serve throughput A/B (20k class, batch 1/8/32, continuous vs sync) ===" | tee -a /dev/stderr >/dev/null
  timeout 5400 python bench.py --serve-throughput \
    --serve-graphs 64 --serve-batch-sizes 1,8,32 \
    --serve-modes continuous,sync --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # staged-ladder + device-carry serve A/B (PR 9): the same 64-graph
  # stream through (a) the staged frontier ladder vs the full-table
  # kernels (+nostage) and (b) host-mirror vs device-resident carry
  # (+devcarry). CPU rows in PERF.md "Staged serve sweeps"; the TPU
  # questions are how much the ladder's frontier-proportional supersteps
  # recover of the ~65 ms/dispatch-amortized batch throughput, and the
  # measured per-slice transfer bytes with the donated carry (the
  # `transfers` slot of the JSON line) where PCIe, not a shared memory
  # bus, prices every host round-trip.
  echo "=== serve staged/devcarry A/B (20k class, batch 1/8/32) ===" | tee -a /dev/stderr >/dev/null
  timeout 7200 python bench.py --serve-throughput \
    --serve-graphs 64 --serve-batch-sizes 1,8,32 \
    --serve-modes continuous,continuous+nostage,continuous+devcarry --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # speculative minimal-k A/B (ROADMAP 4(a)): strict-decrement sweeps
  # with the k-1..k-depth window seated in sibling lanes vs the
  # serve-sequential single_attempt driver over the SAME pool — the
  # outer-k-loop parallelism measurement. The CPU rows (PERF.md
  # "Speculative minimal-k") win 1.7-2.3x purely on per-slice dispatch
  # amortization + claim overlap because CPU lanes scale near-linearly
  # in compute; the TPU question is the real one: sibling lanes are
  # parallel hardware there, so the window should approach
  # ~max(attempt depth) supersteps instead of Σ(attempt depths).
  # Parity (colors + attempt sequences + minimal k vs the off-pool
  # compact reference) is asserted in-run; slice size is the auto
  # policy (prices the ~65 ms on-chip dispatch amortization).
  echo "=== speculative minimal-k A/B (2k class, depth 3/7) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python bench.py --speculate-ab --avg-degree 2.5 \
    --speculate-depth 3 --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
  timeout 3600 python bench.py --speculate-ab --avg-degree 2.5 \
    --speculate-depth 7 --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # mega-dispatch A/B (ROADMAP 5): the blocked minimal-k driver
  # (attempts_per_dispatch=4, strict mode) vs the sequential
  # one-attempt-per-dispatch sweep on the SAME 1M graph. The CPU rows
  # (PERF.md "Dispatch amortization") already prove parity and the
  # >=3x dispatch-count reduction, but CPU wall-clock barely moves
  # because the interpreter overhead per dispatch is microseconds; the
  # TPU question is the real one: each avoided dispatch saves ~65 ms
  # of launch + host round-trip, so a 13->4 dispatch strict chain
  # should recover seconds per sweep. Parity (colors + attempt tuples
  # incl. colors_used) and the dispatch-ratio floor are asserted
  # in-run; the record's `dispatches` slot carries the counter A/B.
  echo "=== mega-dispatch blocked-vs-sequential A/B (1M, A=4) ===" | tee -a /dev/stderr >/dev/null
  timeout 7200 python bench.py --block-ab --nodes 1000000 \
    --block-attempts 4 --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # multi-device serve A/B (ROADMAP 2(a)): the same 64-graph stream
  # with the lane axis sharded over every local chip (+shard: Mesh +
  # NamedSharding over the batch axis, per-device occupancy in the
  # record's `mesh` slot) vs the single-device scheduler. The CPU
  # 8-host-device A/B (PERF.md "Multi-device serve tier") can only
  # prove bit-identity and accounting — forced host devices SHARE one
  # core, so its wall-clock is a prediction, not a result; the TPU
  # question is the real one: does one host serve ~N devices' worth of
  # lanes at the wide batch widths (batch 32/64 over N chips), and
  # where does the per-slice all-reduce of the executed rung start to
  # bite. The sharded parity leg re-proves bit-identity on real chips
  # before the throughput rows are trusted.
  echo "=== multi-device serve A/B (20k class, +shard, batch 8/32/64) ===" | tee -a /dev/stderr >/dev/null
  timeout 1200 env PYTHONPATH=. python tools/bit_identity_ensemble.py --serve \
    --draws 6 --serve-slice-steps 2 --serve-mesh-devices "$(python -c 'import jax; n=len(jax.devices()); print(1 << max(0, n.bit_length()-1))')" \
    --out serve_parity_mesh_tpu.jsonl 2>&1 \
    | tee -a /dev/stderr >/dev/null || true
  timeout 7200 python bench.py --serve-throughput \
    --serve-graphs 64 --serve-batch-sizes 8,32,64 \
    --serve-modes continuous,continuous+shard --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # in-kernel timing column cross-check (PR 7 queued it, PR 11 tooled
  # it): ONE 200k-RMAT run with --superstep-timing (the trajectory
  # buffer's col-5 device wall-time) AND a --profile-window over every
  # dispatch, then tools/xplane_split.py consumes the manifest-linked
  # artifact and emits the timing_crosscheck verdict — the measured
  # answer to whether the callback-based clock is trustworthy on-chip
  # (CPU verdict: ok at coverage ~0.8, PERF.md "Timing-column vs xplane
  # cross-check"). A divergent TPU verdict routes to the ROADMAP native
  # cycle-counter follow-on before the column's absolute values are
  # trusted there.
  echo "=== timing-column vs xplane self-time (200k RMAT) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python -m dgc_tpu.cli --node-count 200000 --max-degree 64 \
    --gen-method rmat --seed 7 --backend ell-compact \
    --output-coloring /tmp/dgc_timing_xcheck.json \
    --run-manifest timing_xcheck_r7.json --superstep-timing \
    --profile-window 1:99 --profile-logdir /tmp/dgc_profile_xcheck 2>&1 \
    | tee -a /dev/stderr >/dev/null || true
  timeout 600 python tools/xplane_split.py timing_xcheck_r7.json \
    --emit-runlog timing_crosscheck_r7.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> trace_attr_r4.jsonl || true

  echo "=== tuned-vs-static A/B (1M RMAT) ===" | tee -a /dev/stderr >/dev/null
  timeout 7200 python bench.py --gen rmat --nodes 1000000 --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
  timeout 7200 python bench.py --gen rmat --nodes 1000000 \
    --tuned-config tools/tuned_configs/rmat_1m.json --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # netfront soak on-chip (PR 12): the 1000-client many-connection
  # harness over the real TPU serving tier — the CPU rows (PERF.md
  # "Network front door") prove the protocol under load; the TPU
  # question is end-to-end graphs/s and in-quota p95 when the lanes
  # are parallel hardware. Zero lost/dup + quota + drain invariants
  # exit nonzero inside the harness; the record accretes into the
  # perf ledger beside the serve A/Bs.
  echo "=== netfront 1000-client soak (TPU serving tier) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/soak.py --clients 1000 --requests-per-client 1 \
    --greedy-clients 100 --nodes 20000 --degree 16 \
    --log-json netfront_soak_tpu.jsonl \
    --run-manifest netfront_soak_tpu_man.json --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # chaos-serve soak on-chip (crash-safe serve tier): the full seeded
  # schedule battery over every serve fault point plus SIGKILL/resume
  # cycles at seeded journal offsets, against the real TPU lanes — the
  # CPU legs (ci_checks.sh smoke + tests/test_chaos_serve.py) prove the
  # protocol; the TPU question is whether recovery stays bit-identical
  # when dispatch aborts land mid-flight on real hardware queues.
  echo "=== chaos-serve soak (TPU kill-resume + serve fault points) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/chaos_serve.py --schedules 10 --kills 3 \
    --clients 8 --requests-per-client 2 --nodes 20000 --degree 16 \
    --deadline 900 --report chaos_serve_tpu.json 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # fleet-telemetry capture on-chip (telemetry plane): one more
  # kill-resume cycle with a KEPT workdir, then the fleet-debugging
  # artifacts are folded out of the wreckage — the per-tenant usage
  # ledger (tools/usage_export.py --check gates on EXACT conservation
  # vs the journal's raw totals; its nonzero exit is the leg's verdict)
  # and the ONE merged Perfetto trace whose request spans cross the
  # kill boundary under the caller's trace id. The CPU smoke
  # (ci_checks.sh step 9) proves this plumbing on toy graphs; this leg
  # proves the trace/usage plane survives a SIGKILL on real hardware
  # queues with in-flight device work.
  echo "=== fleet-telemetry capture (kill-resume usage + merged trace) ===" | tee -a /dev/stderr >/dev/null
  TEL_DIR=$(mktemp -d)
  timeout 1800 python tools/chaos_serve.py --schedules 1 --kills 1 \
    --clients 8 --requests-per-client 2 --nodes 20000 --degree 16 \
    --deadline 900 --workdir "$TEL_DIR" \
    --report chaos_serve_telemetry_tpu.json 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true
  timeout 300 python tools/usage_export.py "$TEL_DIR/journal" \
    --logs "$TEL_DIR/server_*.jsonl" -o usage_tpu.jsonl --check 2>&1 \
    | tee -a /dev/stderr >/dev/null || true
  timeout 300 python tools/export_trace.py "$TEL_DIR"/server_*.jsonl \
    -o trace_merged_tpu.json 2>&1 | tee -a /dev/stderr >/dev/null || true
  rm -rf "$TEL_DIR"

  # chaos-mesh soak on-chip (failure-domain plane): seeded device-loss
  # schedules + single-graph re-shard sweeps + a degraded kill-resume
  # cycle, against the REAL device mesh — the CPU legs (ci_checks.sh
  # smoke + tests/test_mesh_resilience.py) prove the protocol on forced
  # host devices; the TPU question is whether survivor re-sharding
  # stays bit-identical (and how long a degrade's evacuation +
  # recompile actually stalls the serve loop) when the lost "device"
  # is a real chip with in-flight work on its queues. NOTE: injected
  # losses only — on-chip the plane raises InjectedDeviceLoss; a
  # physically-dead chip additionally exercises the message-based
  # classifier (retry._DEVICE_LOSS_MARKERS), which only a real outage
  # can prove.
  echo "=== chaos-mesh soak (device-loss schedules + degraded kill-resume) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/chaos_mesh.py --schedules 6 --sweeps 3 \
    --kill-resume 2 --mesh-devices "$(python -c 'import jax; n=len(jax.devices()); print(1 << max(0, n.bit_length()-1))')" \
    --nodes 20000 --degree 16 --deadline 900 \
    --report chaos_mesh_tpu.json 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # replicated serve fleet on-chip (ROADMAP 2(a) robustness): (a) the
  # chaos-fleet battery — seeded replica-subset SIGKILLs at merged-WAL
  # offsets, the kill-all cold fleet restart, and the brownout tier
  # contract, against real TPU lanes (the CPU legs are ci_checks.sh
  # step 10 + tests/test_fleet.py; the TPU question is whether the
  # cross-incarnation merge replay stays bit-identical when the killed
  # incarnations held real device work) — and (b) the fleet-overhead
  # A/B: soak.py --replicas 2 prices the SO_REUSEPORT fleet against
  # the single listener at batch-8 and gates the overhead SLO (<= 5%)
  # into the perf ledger.
  echo "=== chaos-fleet soak (replica kills + cold fleet restart) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/chaos_fleet.py --replicas 3 --kills 3 \
    --clients 8 --requests-per-client 2 --nodes 20000 --degree 16 \
    --deadline 900 --report chaos_fleet_tpu.json 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  echo "=== fleet-overhead A/B (soak --replicas 2, batch-8 SLO gate) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/soak.py --replicas 2 --clients 64 \
    --requests-per-client 4 --nodes 20000 --degree 16 --batch-max 8 \
    --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  # result-cache A/B on-chip (content-addressed result cache): the CPU
  # rows (PERF.md "Content-addressed result cache") prove the hit path
  # at memcpy speed against a ~35ms CPU compute; the TPU question is
  # the same ratio against real accelerator latency AND that the 0%-
  # duplicate overhead stays <=2% when admission is fed by parallel
  # hardware lanes. Both SLO gates exit nonzero inside the harness.
  echo "=== result-cache A/B (soak --cache-ab, 60% duplicates, 20k class) ===" | tee -a /dev/stderr >/dev/null
  timeout 3600 python tools/soak.py --cache-ab --ab-trials 3 \
    --duplicate-pct 60 --clients 64 --requests-per-client 4 \
    --nodes 20000 --degree 16 --batch-max 8 --result-cache 512 \
    --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' >> "$OUT" || true

  echo "=== cold compile, unified pipeline 1M-RMAT ===" | tee -a /dev/stderr >/dev/null
  # fresh cache dir = genuinely cold compile (removed after); outer
  # timeout sits ABOVE bench.py's 5400s in-process deadline so the
  # cleaner labeled abort always wins; aborted records stay out of the
  # jsonl like the battery's
  COLD_CACHE=$(mktemp -d)
  JAX_COMPILATION_CACHE_DIR="$COLD_CACHE" timeout 6000 \
    python bench.py --gen rmat --nodes 1000000 --include-compile --perf-db PERF_DB.jsonl 2>&1 \
    | tee -a /dev/stderr | grep '^{' | grep -v '"bench_aborted' >> "$OUT" || true
  rm -rf "$COLD_CACHE"
fi

echo "evidence capture done (battery rc=$battery_rc)" >&2
exit "$battery_rc"

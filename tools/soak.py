#!/usr/bin/env python
"""Many-client soak harness for the network front door
(``dgc_tpu.serve.netfront``).

Stands a full serving stack in-process — ``ServeFrontEnd`` + admission
control + the one-port listener — and drives it with N concurrent HTTP
clients (one persistent connection per client thread; ``--clients
1000`` means 1000 live sockets), proving the acceptance contract the
ROADMAP's "millions of users" claim rests on:

- **zero lost or duplicated results** — every accepted ticket returns
  exactly one terminal result, every ticket id is unique, and the
  server-side completed count matches the client-side account;
- **per-tenant quotas enforced** — ``--greedy-clients`` run under a
  rate-limited tenant and MUST see 429s carrying structured retry
  context (``retry_after_s``, token state) while the in-quota tenant's
  requests all land;
- **graceful drain under load** — with ``--drain`` (default) the
  harness POSTs ``/admin/drain`` once every submission is accepted but
  while requests are still in flight; all of them must still complete
  and remain pollable after the drain.

Artifacts: the server-side run log (``--log-json``, schema-validated by
``tools/validate_runlog.py``) and manifest (``--run-manifest``) feed
``tools/slo_check.py`` — the SLO gate over the soak — and the one JSON
record printed to stdout feeds ``tools/perf_db.py`` (or pass
``--perf-db`` to append + regression-check directly), so "multi-tenant
serving under load" is a ledgered number. ``tools/ci_checks.sh`` runs a
small-count smoke of exactly this pipeline; the 1000-client CPU row
lives in PERF.md ("Network front door").

Usage:
  JAX_PLATFORMS=cpu python tools/soak.py --clients 1000 --nodes 120 \\
      --degree 6 --log-json soak.jsonl --run-manifest soak_man.json \\
      --perf-db PERF_DB.jsonl
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# client-side retry bound: a 429'd submit retries with the server's
# retry_after_s hint (exponentially backed off — the hint prices ONE
# token, not the whole herd contending for it) this many times before
# counting as shed for good
MAX_SUBMIT_RETRIES = 100

# --duplicate-pct traffic draws its graph specs from this fixed seed
# pool (seeded per client+request, so a soak is reproducible): a small
# pool keeps the duplicate share genuinely content-identical — the
# result cache's hit case — instead of merely statistically similar
DUP_SEED_POOL = (1, 2, 3, 4)


class _Client:
    """One soak client: a persistent connection submitting then polling
    its own requests. All fields are thread-confined to the client's
    thread; the harness reads them only after join()."""

    def __init__(self, idx: int, port: int, tenant: str, args):
        self.idx = idx
        self.port = port
        self.tenant = tenant
        self.args = args
        self.tickets: list = []        # accepted ticket ids, in order
        self.results: dict = {}        # ticket -> result doc
        self.rejects: list = []        # structured 429 bodies
        self.shed = 0                  # submits given up after retries
        self.client_ms: list = []      # accept -> terminal result, ms
        self.dup_tickets: set = set()  # tickets from the duplicate pool
        self.errors: list = []

    def _request(self, method, path, doc=None, headers_extra=None):
        """One request on the client's persistent connection, retrying
        transient socket failures (the connect herd of a 1000-client
        ramp can outrun even a deep accept backlog) on a fresh
        connection with jittered backoff."""
        body = json.dumps(doc).encode() if doc is not None else None
        headers = {"X-Dgc-Tenant": self.tenant}
        if body is not None:
            headers["Content-Type"] = "application/json"
        if headers_extra:
            headers.update(headers_extra)
        last = None
        for attempt in range(8):
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        "127.0.0.1", self.port, timeout=120)
                self._conn.request(method, path, body=body,
                                   headers=headers)
                resp = self._conn.getresponse()
                payload = resp.read()
                return resp.status, (json.loads(payload) if payload
                                     else {})
            except (OSError, http.client.HTTPException) as e:
                last = e
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                self._conn = None
                time.sleep(0.05 * (attempt + 1) + (self.idx % 17) * 1e-3)
        raise last

    def run(self, submit_barrier: threading.Barrier) -> None:
        self._conn = None
        try:
            # phase 1: submit everything (retrying on backpressure)
            for r in range(self.args.requests_per_client):
                # --duplicate-pct: a seeded per-request draw sends this
                # share of traffic to the fixed duplicate seed pool —
                # the content-identical repeat pattern the result cache
                # and single-flight coalescing target
                seed, dup = self.idx * 10_000 + r, False
                dup_pct = getattr(self.args, "duplicate_pct", 0.0)
                if dup_pct > 0:
                    rng = random.Random(self.idx * 100_003 + r)
                    if rng.random() * 100.0 < dup_pct:
                        seed = DUP_SEED_POOL[
                            rng.randrange(len(DUP_SEED_POOL))]
                        dup = True
                doc = {"node_count": self.args.nodes,
                       "max_degree": self.args.degree,
                       "seed": seed,
                       "gen_method": "fast"}
                tp = None
                if self.args.telemetry:
                    # deterministic per-request W3C trace context — the
                    # propagation cost rides every submit, like a fleet
                    # router stamping each hop
                    h = hashlib.sha256(
                        f"soak-{self.idx}-{r}".encode()).hexdigest()
                    tp = {"traceparent": f"00-{h[:32]}-{h[32:48]}-01"}
                accepted = False
                for _attempt in range(MAX_SUBMIT_RETRIES):
                    status, body = self._request("POST", "/v1/color",
                                                 doc, headers_extra=tp)
                    if status == 202:
                        self.tickets.append(
                            (body["ticket"], time.perf_counter()))
                        if dup:
                            self.dup_tickets.add(body["ticket"])
                        accepted = True
                        break
                    if status == 429:
                        self.rejects.append(body)
                        hint = float(body.get("retry_after_s") or 0.1)
                        time.sleep(min(2.0, max(hint, 0.05)
                                       * (1 << min(_attempt, 5))))
                        continue
                    self.errors.append(f"submit HTTP {status}: {body}")
                    break
                if not accepted and not self.errors:
                    self.shed += 1
        except Exception as e:   # noqa: BLE001 — harness accounting
            self.errors.append(f"{type(e).__name__}: {e}")
        finally:
            # rendezvous UNCONDITIONALLY: the harness drains only after
            # every client finished submitting, and a failed client
            # must not wedge the barrier
            try:
                submit_barrier.wait(timeout=600)
            except threading.BrokenBarrierError:
                self.errors.append("submit barrier broken")
        try:
            # phase 2: poll every accepted ticket to a terminal result
            for ticket, t_accept in self.tickets:
                while True:
                    status, body = self._request(
                        "GET", f"/v1/result/{ticket}")
                    if status == 200:
                        if ticket in self.results:
                            self.errors.append(f"duplicate {ticket}")
                        self.results[ticket] = body
                        self.client_ms.append(
                            (time.perf_counter() - t_accept) * 1e3)
                        break
                    if status == 202:
                        time.sleep(0.05)
                        continue
                    self.errors.append(f"poll {ticket} HTTP {status}")
                    break
            if self._conn is not None:
                self._conn.close()
        except Exception as e:   # noqa: BLE001 — harness accounting
            self.errors.append(f"{type(e).__name__}: {e}")


def _one_shot(port: int, method: str, path: str, doc=None,
              deadline_s: float = 180.0):
    """One request against a subprocess serve leg, retried through
    connection failures (the server may still be binding). Returns
    (status, body_doc)."""
    body = json.dumps(doc).encode() if doc is not None else None
    headers = {"Content-Type": "application/json"} if body else {}
    t_end = time.perf_counter() + deadline_s
    last: Exception | None = None
    while time.perf_counter() < t_end:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, (json.loads(payload) if payload else {})
        except (OSError, http.client.HTTPException) as e:
            last = e
            time.sleep(0.1)
        finally:
            conn.close()
    raise RuntimeError(f"port {port} unreachable: {last}")


def _serve_leg(args, replicas: int, workdir: str) -> tuple[dict, list]:
    """Soak ONE subprocess serve tier — a single listener
    (``replicas == 1``) or a fleet (``serve --replicas N``) — with the
    same client pool, so the two legs' graphs/s are an apples-to-apples
    A/B. Returns (facts, problems)."""
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    journal = os.path.join(workdir, f"journal_x{replicas}")
    cmd = [sys.executable, "-m", "dgc_tpu.cli", "serve",
           "--listen", str(port), "--journal-dir", journal,
           "--batch-max", str(args.batch_max),
           "--queue-depth", str(args.queue_depth),
           "--window-ms", str(args.window_ms)]
    if replicas >= 2:
        cmd += ["--replicas", str(replicas)]
    # compile off the A/B clock: EVERY replica pre-warms the soak's one
    # shape class at startup (readiness gates on it), so the fleet isn't
    # charged N-1 extra JIT warmups the single listener doesn't pay
    from dgc_tpu.models.graph import Graph
    from dgc_tpu.serve.shape_classes import DEFAULT_LADDER

    probe = Graph.generate(args.nodes, args.degree, seed=0,
                           method="fast")
    cls = DEFAULT_LADDER.class_for(probe.num_vertices,
                                   probe.arrays.max_degree)
    if cls is not None:
        cmd += ["--warm-classes", cls.name]
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    problems: list = []
    facts: dict = {"replicas": replicas}
    try:
        _one_shot(port, "GET", "/healthz")
        clients = [_Client(i, port, "load", args)
                   for i in range(args.clients)]
        barrier = threading.Barrier(args.clients + 1)
        threads = [threading.Thread(target=c.run, args=(barrier,),
                                    name=f"soak-fleet-{c.idx}",
                                    daemon=True) for c in clients]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        barrier.wait(timeout=600)
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0

        all_tickets = [tk for c in clients for tk, _ in c.tickets]
        accepted = len(all_tickets)
        if len(set(all_tickets)) != accepted:
            problems.append(
                f"x{replicas}: duplicate ticket ids fleet-wide")
        done = sum(len(c.results) for c in clients)
        ok = sum(1 for c in clients for r in c.results.values()
                 if r.get("status") == "ok")
        if done != accepted or ok != accepted:
            problems.append(f"x{replicas}: {accepted} accepted, {done} "
                            f"polled, {ok} ok")
        for c in clients:
            problems.extend(f"x{replicas}: {e}" for e in c.errors)
        # clients are done — drain through the front door (one replica
        # takes it; the supervisor follows it down) and require a clean
        # fleet exit
        _one_shot(port, "POST", "/admin/drain", {})
        rc = proc.wait(timeout=300)
        if rc != 0:
            problems.append(f"x{replicas}: serve tier exited rc {rc}")
        client_ms = [ms for c in clients for ms in c.client_ms]
        facts.update(
            requests=accepted, wall_s=round(wall, 3),
            value=round(accepted / wall, 3) if wall > 0 else None,
            p95_client_ms=(round(_pctile(client_ms, 0.95), 3)
                           if client_ms else None))
        return facts, problems
    except (RuntimeError, threading.BrokenBarrierError) as e:
        problems.append(f"x{replicas}: {e}")
        facts.setdefault("value", None)
        return facts, problems
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)


# the replicated-tier tax budget: the fleet's graphs/s may trail the
# single listener's by at most this much at the same batch ceiling
FLEET_OVERHEAD_SLO_PCT = 5.0


def _fleet_ab(args) -> int:
    """``--replicas N``: the fleet A/B. Soak a single subprocess
    listener, then an N-replica fleet, with identical client pools;
    emit ONE perf record (the fleet row, baseline attached) gated on
    the fleet-overhead SLO."""
    import shutil
    import tempfile

    workdir = tempfile.mkdtemp(prefix="dgc_soak_fleet_")

    def best_of(replicas: int) -> tuple[dict, list]:
        # throughput = best of K trials per leg (scheduler noise on a
        # shared box swamps a one-shot A/B); correctness problems from
        # EVERY trial count — a lost ticket is real no matter the trial
        best: dict = {}
        probs: list = []
        for trial in range(max(1, args.ab_trials)):
            facts, trial_probs = _serve_leg(
                args, replicas,
                os.path.join(workdir, f"x{replicas}_t{trial}"))
            probs.extend(trial_probs)
            if facts.get("value") and facts["value"] > best.get(
                    "value", 0.0):
                best = facts
        return best or facts, probs

    try:
        base_facts, problems = best_of(1)
        fleet_facts, fleet_problems = best_of(args.replicas)
        problems.extend(fleet_problems)
        overhead = None
        if base_facts.get("value") and fleet_facts.get("value"):
            overhead = round(
                100.0 * (base_facts["value"] - fleet_facts["value"])
                / base_facts["value"], 2)
            if overhead > FLEET_OVERHEAD_SLO_PCT:
                problems.append(
                    f"fleet overhead {overhead}% > "
                    f"{FLEET_OVERHEAD_SLO_PCT}% SLO "
                    f"(single {base_facts['value']} vs fleet "
                    f"{fleet_facts['value']} graphs/s)")
        record = {
            "metric": f"soak_netfront_fleet{args.replicas}"
                      f"_c{args.clients}_r{args.requests_per_client}"
                      f"_n{args.nodes}d{args.degree}",
            "value": fleet_facts.get("value"),
            "unit": "graphs/s",
            "backend": "netfront_fleet",
            "platform": _platform(),
            "replicas": args.replicas,
            "clients": args.clients,
            "requests": fleet_facts.get("requests"),
            "p95_client_ms": fleet_facts.get("p95_client_ms"),
            "wall_s": fleet_facts.get("wall_s"),
            "single_value": base_facts.get("value"),
            "fleet_overhead_pct": overhead,
            "slo_fleet_overhead_pct_max": FLEET_OVERHEAD_SLO_PCT,
            "soak_ok": not problems,
        }
        rc = 0
        for prob in problems:
            print(f"SOAK FAIL: {prob}", file=sys.stderr)
            rc = 1
        if args.perf_db and not problems and record["value"] is not None:
            from tools.perf_db import record_and_check, render_verdict

            verdict = record_and_check(args.perf_db, record)
            print(render_verdict(verdict), file=sys.stderr)
            if verdict.get("regression"):
                rc = 1
        print(json.dumps(record))
        return rc
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _platform() -> str | None:
    try:
        import jax

        return jax.default_backend()
    except Exception:   # noqa: BLE001 — record stays writable without jax
        return None


def _pctile(xs: list, q: float) -> float | None:
    if not xs:
        return None
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(q * len(ys)))]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=1000,
                   help="concurrent client connections (default 1000)")
    p.add_argument("--requests-per-client", type=int, default=1)
    p.add_argument("--greedy-clients", type=int, default=None,
                   help="clients assigned to the rate-limited 'greedy' "
                        "tenant to prove quota enforcement (default: "
                        "clients // 10)")
    p.add_argument("--nodes", type=int, default=120,
                   help="vertices per generated request graph")
    p.add_argument("--degree", type=int, default=6)
    p.add_argument("--batch-max", type=int, default=8)
    p.add_argument("--queue-depth", type=int, default=256)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--window-ms", type=float, default=2.0)
    p.add_argument("--tenants", type=str, default=None,
                   help="tenant config JSON (inline or path); default: "
                        "a permissive 'load' tenant + a rate-limited "
                        "'greedy' tenant (rate 5/s, burst 5)")
    p.add_argument("--no-drain", action="store_true",
                   help="skip the mid-soak graceful drain")
    p.add_argument("--journal-dir", type=str, default=None,
                   help="durable ticket journal directory (the crash-"
                        "safe serve tier): every accepted submit is "
                        "fsync-journaled ahead of its 202 — the "
                        "journal-on vs journal-off throughput delta is "
                        "the PERF.md \"Durable ticket journal\" row")
    p.add_argument("--telemetry", action="store_true",
                   help="arm the fleet-telemetry plane under load: a "
                        "1s timeseries sampler on the listener AND a "
                        "per-request W3C traceparent header from every "
                        "client — the on/off A/B is the PERF.md "
                        "\"Fleet telemetry overhead\" row")
    p.add_argument("--duplicate-pct", type=float, default=0.0,
                   metavar="P",
                   help="percent of traffic drawn from a fixed "
                        f"{len(DUP_SEED_POOL)}-seed duplicate pool "
                        "(seeded per client+request — reproducible): "
                        "the content-identical repeat pattern the "
                        "result cache serves at memcpy speed")
    p.add_argument("--result-cache", type=int, default=0, metavar="N",
                   help="arm the content-addressed result cache on the "
                        "in-process listener with an N-entry LRU "
                        "(0 = off, the byte-identical baseline)")
    p.add_argument("--result-cache-dir", type=str, default=None,
                   metavar="DIR",
                   help="optional shared on-disk store behind the "
                        "result cache")
    p.add_argument("--cache-ab", action="store_true",
                   help="run the result-cache A/B: a duplicate-heavy "
                        "leg (≥50%% duplicates; cache must win "
                        f"{CACHE_SPEEDUP_SLO_X}x on duplicate-side p50 "
                        "served latency or throughput) and a "
                        "0%%-duplicate leg (cache may cost at most "
                        f"{CACHE_OVERHEAD_SLO_PCT}%% throughput), each "
                        "soaked cache-off then cache-on; both rows "
                        "append to --perf-db")
    p.add_argument("--cache-speedup-slo", type=float,
                   default=CACHE_SPEEDUP_SLO_X, metavar="X",
                   help="override the --cache-ab speedup gate "
                        f"(default {CACHE_SPEEDUP_SLO_X}x)")
    p.add_argument("--cache-overhead-slo", type=float,
                   default=CACHE_OVERHEAD_SLO_PCT, metavar="PCT",
                   help="override the --cache-ab overhead gate "
                        f"(default {CACHE_OVERHEAD_SLO_PCT}%%) — CI "
                        "smokes at second-scale walls loosen this to a "
                        "structural bound; the measured ≤"
                        f"{CACHE_OVERHEAD_SLO_PCT}%% row comes from "
                        "the full-size A/B (PERF.md)")
    p.add_argument("--replicas", type=int, default=1,
                   help="N >= 2 switches to the fleet A/B: soak a "
                        "single subprocess listener, then a "
                        "``serve --replicas N`` fleet on one "
                        "SO_REUSEPORT port, and gate the fleet's "
                        "graphs/s within the fleet-overhead SLO "
                        f"({FLEET_OVERHEAD_SLO_PCT}% of the single "
                        "listener's)")
    p.add_argument("--ab-trials", type=int, default=3,
                   help="trials per fleet-A/B leg; throughput is the "
                        "best trial (damps scheduler noise), "
                        "correctness failures from any trial count")
    p.add_argument("--log-json", type=str, default=None)
    p.add_argument("--run-manifest", type=str, default=None)
    p.add_argument("--perf-db", type=str, default=None,
                   help="append the soak record to this perf ledger "
                        "(tools/perf_db.py) and exit 1 on regression")
    args = p.parse_args(argv)

    if args.cache_ab:
        return _cache_ab(args)
    if args.replicas >= 2:
        return _fleet_ab(args)
    record, problems = _soak_core(args)
    rc = 0
    for prob in problems:
        print(f"SOAK FAIL: {prob}", file=sys.stderr)
        rc = 1
    if args.perf_db and not problems and record["value"] is not None:
        from tools.perf_db import record_and_check, render_verdict

        verdict = record_and_check(args.perf_db, record)
        print(render_verdict(verdict), file=sys.stderr)
        if verdict.get("regression"):
            rc = 1
    print(json.dumps(record))
    return rc


def _soak_core(args) -> tuple[dict, list]:
    """Stand the full in-process single-listener stack and soak it with
    ``args.clients`` concurrent connections: the reusable body behind
    the plain soak, and both legs of the ``--cache-ab`` comparison.
    Returns ``(record, problems)``."""
    from dgc_tpu.obs import MetricsRegistry, RunLogger, RunManifest
    from dgc_tpu.serve.netfront import (AdmissionController, NetFront,
                                        load_tenant_configs)
    from dgc_tpu.serve.queue import ServeFrontEnd
    from dgc_tpu.serve.shape_classes import DEFAULT_LADDER

    if args.tenants:
        raw = args.tenants
        if not raw.lstrip().startswith("{"):
            raw = open(raw).read()
        tenant_doc = json.loads(raw)
    else:
        tenant_doc = {"default": {},
                      "tenants": {"load": {"tier": "paid"},
                                  "greedy": {"rate": 5.0, "burst": 5.0}}}
    greedy = (args.greedy_clients if args.greedy_clients is not None
              else args.clients // 10)

    logger = RunLogger(jsonl_path=args.log_json, echo=False)
    registry = MetricsRegistry()
    manifest = RunManifest()
    logger.add_sink(manifest)
    front = ServeFrontEnd(batch_max=args.batch_max,
                          window_s=args.window_ms / 1e3,
                          queue_depth=args.queue_depth,
                          workers=args.workers,
                          logger=logger, registry=registry).start()
    admission = AdmissionController(load_tenant_configs(tenant_doc),
                                    registry=registry, logger=logger)
    sampler = None
    if args.telemetry:
        from dgc_tpu.obs.timeseries import TimeseriesSampler

        sampler = TimeseriesSampler(registry, interval_s=1.0).start()
    resultcache = None
    if args.result_cache > 0:
        from dgc_tpu.serve.resultcache import ResultCache

        resultcache = ResultCache(args.result_cache,
                                  cache_dir=args.result_cache_dir)
    nf = NetFront(front, admission=admission, registry=registry,
                  logger=logger, journal_dir=args.journal_dir,
                  timeseries=sampler, resultcache=resultcache).start()

    # compile off the soak clock: warm the one shape class the soak's
    # generator spec lands in (the --warm-classes convention)
    warm_s = None
    from dgc_tpu.models.graph import Graph

    probe = Graph.generate(args.nodes, args.degree, seed=0, method="fast")
    cls = DEFAULT_LADDER.class_for(probe.num_vertices,
                                   probe.arrays.max_degree)
    if cls is not None:
        warm_s = front.warm([cls.name])["seconds"]

    # --cache-ab's speedup leg models STEADY-STATE repeat traffic (the
    # ROADMAP 2(c) regime: recurring graphs over a long-lived tier):
    # the duplicate pool is submitted once and polled to completion OFF
    # the clock, so the measured window sees warm-cache hits instead of
    # first-sight computes. The cache-off baseline runs the same
    # pre-pass — identical work, it just cannot keep the results
    prewarmed = 0
    if getattr(args, "prewarm_dup_pool", False) and args.duplicate_pct:
        for seed in DUP_SEED_POOL:
            st_code, body = _one_shot(
                nf.port, "POST", "/v1/color",
                {"node_count": args.nodes, "max_degree": args.degree,
                 "seed": seed, "gen_method": "fast"})
            if st_code != 202:
                continue
            prewarmed += 1
            t_end = time.perf_counter() + 120
            while time.perf_counter() < t_end:
                st_code, _ = _one_shot(
                    nf.port, "GET", f"/v1/result/{body['ticket']}")
                if st_code != 202:
                    break
                time.sleep(0.02)

    clients = [_Client(i, nf.port,
                       "greedy" if i < greedy else "load", args)
               for i in range(args.clients)]
    # parties: every client + the harness thread (drain rendezvous)
    barrier = threading.Barrier(args.clients + 1)
    threads = [threading.Thread(target=c.run, args=(barrier,),
                                name=f"soak-client-{c.idx}", daemon=True)
               for c in clients]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    try:
        barrier.wait(timeout=600)
    except threading.BrokenBarrierError:
        print("SOAK: submit barrier broken (client failures); "
              "draining anyway", file=sys.stderr)
    # every submission is in (accepted or accounted); drain while the
    # tail is still in flight — the graceful-drain-under-load proof
    drain_doc = None
    if not args.no_drain:
        drain_doc = nf.drain(timeout=300)
    for t in threads:
        t.join(timeout=600)
    wall = time.perf_counter() - t0

    # -- the zero-lost / zero-dup account --------------------------------
    problems: list = []
    all_tickets = [tk for c in clients for tk, _ in c.tickets]
    accepted = len(all_tickets)
    if len(set(all_tickets)) != accepted:
        problems.append("duplicate ticket ids issued")
    done = sum(len(c.results) for c in clients)
    ok = sum(1 for c in clients for r in c.results.values()
             if r.get("status") == "ok")
    if done != accepted:
        problems.append(f"lost results: {accepted} accepted, {done} "
                        f"polled to completion")
    if ok != accepted:
        problems.append(f"non-ok results: {ok}/{accepted} ok")
    for c in clients:
        problems.extend(c.errors)
    st = front.stats_snapshot()
    # cache-served requests (hits + coalesced followers) never reach
    # the front end; promoted followers compute after all — the exact
    # account the result cache's stats make checkable
    expected_computed = accepted + prewarmed
    if resultcache is not None:
        snap = resultcache.snapshot()
        expected_computed = (accepted + prewarmed - snap["hits"]
                             - snap["coalesced"] + snap["promotions"])
    if st["completed"] != expected_computed:
        problems.append(f"server completed {st['completed']} != "
                        f"{expected_computed} expected "
                        f"({accepted} accepted)")
    rejects = [r for c in clients for r in c.rejects]
    rate_limited = [r for r in rejects
                    if r.get("reason") == "rate_limited"]
    if greedy > 0 and args.requests_per_client * greedy > 5:
        if not rate_limited:
            problems.append("greedy tenant never rate-limited "
                            "(quota not enforced?)")
        elif not all(r.get("retry_after_s") is not None
                     for r in rate_limited):
            problems.append("429 without retry_after_s context")
    shed = sum(c.shed for c in clients)
    if shed:
        problems.append(f"{shed} submits shed after "
                        f"{MAX_SUBMIT_RETRIES} retries")
    if drain_doc is not None and not drain_doc.get("drained"):
        problems.append(f"drain failed: {drain_doc}")

    client_ms = [ms for c in clients for ms in c.client_ms]
    # served latency (queue + service, the server-side cost of one
    # request) split by traffic class: the duplicate share is exactly
    # what the result cache accelerates, so the --cache-ab speedup
    # gates on the duplicate-side p50
    dup_ms, uniq_ms = [], []
    for c in clients:
        for tk, body in c.results.items():
            served = (float(body.get("queue_ms") or 0.0)
                      + float(body.get("service_ms") or 0.0))
            (dup_ms if tk in c.dup_tickets else uniq_ms).append(served)
    record = {
        "metric": f"soak_netfront_c{args.clients}"
                  f"_r{args.requests_per_client}"
                  f"_n{args.nodes}d{args.degree}"
                  + ("_journal" if args.journal_dir else "")
                  + ("_telemetry" if args.telemetry else "")
                  + (f"_dup{args.duplicate_pct:g}"
                     if args.duplicate_pct else "")
                  + ("_cache" if resultcache is not None else ""),
        "journal": bool(args.journal_dir),
        "telemetry": args.telemetry,
        "value": round(accepted / wall, 3) if wall > 0 else None,
        "unit": "graphs/s",
        "backend": "netfront",
        "platform": _platform(),
        "serve_mode": front.scheduler.mode,
        "clients": args.clients,
        "requests": accepted,
        "rejected_429": len(rejects),
        "rate_limited": len(rate_limited),
        "p95_client_ms": (round(_pctile(client_ms, 0.95), 3)
                          if client_ms else None),
        "duplicate_pct": args.duplicate_pct,
        "p50_dup_served_ms": (round(_pctile(dup_ms, 0.5), 3)
                              if dup_ms else None),
        "p50_uniq_served_ms": (round(_pctile(uniq_ms, 0.5), 3)
                               if uniq_ms else None),
        "result_cache": args.result_cache,
        "wall_s": round(wall, 3),
        "warmup_s": warm_s,
        "drain_wall_s": drain_doc.get("wall_s") if drain_doc else None,
        "soak_ok": not problems,
    }
    if resultcache is not None:
        snap = resultcache.snapshot()
        record["cache_hits"] = snap["hits"]
        record["cache_coalesced"] = snap["coalesced"]
        record["cache_stores"] = snap["stores"]

    front.health(emit=True)
    if args.no_drain:
        front.shutdown(drain=True)
    nf.close()
    if sampler is not None:
        sampler.close()
    if args.run_manifest:
        manifest.finalize(registry=registry)
        manifest.write(args.run_manifest)
        logger.event("manifest_written", path=args.run_manifest)
    logger.close()
    return record, problems


# --cache-ab SLO constants: the duplicate-heavy leg must show at least
# CACHE_SPEEDUP_SLO_X× on duplicate-side p50 served latency OR total
# throughput; the 0%-duplicate leg may cost at most
# CACHE_OVERHEAD_SLO_PCT of throughput (the hash-per-submit tax)
CACHE_SPEEDUP_SLO_X = 5.0
CACHE_OVERHEAD_SLO_PCT = 2.0
CACHE_AB_DEFAULT_CAPACITY = 512


def _cache_ab(args) -> int:
    """``--cache-ab``: the result-cache A/B. Two legs, each soaked
    cache-off then cache-on with identical seeded client pools:

    - **speedup** at ``--duplicate-pct`` (floored at 50): the cache must
      win ≥ ``CACHE_SPEEDUP_SLO_X``× on duplicate-side p50 served
      latency or on throughput;
    - **overhead** at 0% duplicates: pure-unique traffic may lose at
      most ``CACHE_OVERHEAD_SLO_PCT``% throughput to the per-submit
      content hash.

    Emits one perf record per leg (cache-off baseline attached), both
    appended to ``--perf-db``. Throughput/latency are best-of
    ``--ab-trials`` per side; correctness problems from every trial
    count."""
    cap = args.result_cache or CACHE_AB_DEFAULT_CAPACITY

    def leg(dup_pct: float, cache_on: bool) -> tuple[dict, list]:
        best: dict = {}
        probs: list = []
        for _trial in range(max(1, args.ab_trials)):
            sub = argparse.Namespace(**vars(args))
            sub.duplicate_pct = dup_pct
            sub.result_cache = cap if cache_on else 0
            sub.greedy_clients = 0     # quota 429s would skew the A/B
            sub.prewarm_dup_pool = dup_pct > 0
            sub.log_json = sub.run_manifest = sub.perf_db = None
            record, trial_probs = _soak_core(sub)
            probs.extend(trial_probs)
            if record.get("value") and record["value"] > best.get(
                    "value", 0.0):
                best = record
        return best or record, probs

    dup_pct = max(50.0, args.duplicate_pct or 0.0)
    problems: list = []
    legs: dict = {}
    for name, pct, on in (("dup_off", dup_pct, False),
                          ("dup_on", dup_pct, True),
                          ("uniq_off", 0.0, False),
                          ("uniq_on", 0.0, True)):
        legs[name], probs = leg(pct, on)
        problems.extend(f"{name}: {p}" for p in probs)

    def ratio(num, den):
        return (round(num / den, 2)
                if num is not None and den else None)

    speedup_p50 = ratio(legs["dup_off"].get("p50_dup_served_ms"),
                        legs["dup_on"].get("p50_dup_served_ms"))
    speedup_tput = ratio(legs["dup_on"].get("value"),
                         legs["dup_off"].get("value"))
    speedup_slo = getattr(args, "cache_speedup_slo", CACHE_SPEEDUP_SLO_X)
    overhead_slo = getattr(args, "cache_overhead_slo",
                           CACHE_OVERHEAD_SLO_PCT)
    best_speedup = max(filter(None, (speedup_p50, speedup_tput)),
                      default=None)
    if best_speedup is None or best_speedup < speedup_slo:
        problems.append(
            f"cache speedup {best_speedup}x < {speedup_slo}x "
            f"SLO at {dup_pct:g}% duplicates (p50 {speedup_p50}x, "
            f"throughput {speedup_tput}x)")
    overhead = None
    if legs["uniq_off"].get("value") and legs["uniq_on"].get("value"):
        overhead = round(
            100.0 * (legs["uniq_off"]["value"] - legs["uniq_on"]["value"])
            / legs["uniq_off"]["value"], 2)
        if overhead > overhead_slo:
            problems.append(
                f"cache overhead {overhead}% > "
                f"{overhead_slo}% SLO at 0% duplicates "
                f"(off {legs['uniq_off']['value']} vs on "
                f"{legs['uniq_on']['value']} graphs/s)")
    base = (f"_c{args.clients}_r{args.requests_per_client}"
            f"_n{args.nodes}d{args.degree}")
    records = [
        {"metric": f"soak_cache_speedup{base}_dup{dup_pct:g}",
         "value": best_speedup, "unit": "x",
         "backend": "netfront_cache", "platform": _platform(),
         "duplicate_pct": dup_pct, "result_cache": cap,
         "speedup_p50_x": speedup_p50,
         "speedup_throughput_x": speedup_tput,
         "p50_dup_served_ms_off": legs["dup_off"].get(
             "p50_dup_served_ms"),
         "p50_dup_served_ms_on": legs["dup_on"].get(
             "p50_dup_served_ms"),
         "graphs_s_off": legs["dup_off"].get("value"),
         "graphs_s_on": legs["dup_on"].get("value"),
         "cache_hits": legs["dup_on"].get("cache_hits"),
         "cache_coalesced": legs["dup_on"].get("cache_coalesced"),
         "slo_speedup_x_min": speedup_slo,
         "soak_ok": not problems},
        {"metric": f"soak_cache_overhead{base}",
         "value": overhead, "unit": "pct", "better": "lower",
         "backend": "netfront_cache", "platform": _platform(),
         "duplicate_pct": 0.0, "result_cache": cap,
         "graphs_s_off": legs["uniq_off"].get("value"),
         "graphs_s_on": legs["uniq_on"].get("value"),
         "slo_overhead_pct_max": overhead_slo,
         "soak_ok": not problems},
    ]
    rc = 0
    for prob in problems:
        print(f"SOAK FAIL: {prob}", file=sys.stderr)
        rc = 1
    for record in records:
        if args.perf_db and not problems and record["value"] is not None:
            from tools.perf_db import record_and_check, render_verdict

            verdict = record_and_check(args.perf_db, record)
            print(render_verdict(verdict), file=sys.stderr)
            if verdict.get("regression"):
                rc = 1
        print(json.dumps(record))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

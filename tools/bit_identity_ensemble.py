"""Seeded bit-identity ensemble for the segmented-gather plan (chip-free).

The segmented plan's contract is *bit-identity by construction*: same
slots, same clip widths, same ``beats_rule`` adjudication — only the
gather batching changed. This tool checks it the hard way on seeded
draws, uniform + RMAT (RMAT draws are uncapped — the heavy tail is
whatever the generator produces):

- colors AND superstep counts of the staged ``ell-compact`` engine equal
  ``ell-bucketed``'s (the bit-identity anchor the pre-PR compact engine
  was tested against, unchanged by the segmented plan — equality here is
  equality with the pre-PR compact engine);
- telemetry on == telemetry off (the trajectory carry must be inert);
- the fused ``sweep`` pair (prefix-resume included) equals two plain
  ``attempt`` calls.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/bit_identity_ensemble.py \
        [--nodes 20000] [--draws 12] [--out tools/seg_parity.jsonl]

``--tuned-config PATH`` runs every compact engine under a tuned schedule
(``dgc_tpu.tune``) instead of the shipped defaults — the tuner's knobs
are result-invariant by construction, and this is the harness that
checks it the hard way: colors AND superstep counts must still equal
``ell-bucketed``'s on every draw (``tools/tune_parity_20k.jsonl`` is a
committed run under a non-default config; the graph-shape-hash mismatch
across draws is expected and warns — schedules stay exact on any graph).

One JSON line per draw, nonzero exit on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import warnings


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=20_000)
    p.add_argument("--draws", type=int, default=12)
    p.add_argument("--avg-degree", type=float, default=16.0)
    p.add_argument("--seed0", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    p.add_argument("--tuned-config", type=str, default=None,
                   help="tuned-config artifact applied to every compact "
                        "engine (bit-identity must hold under ANY config)")
    args = p.parse_args()

    import numpy as np

    from dgc_tpu.engine.bucketed import BucketedELLEngine
    from dgc_tpu.engine.compact import CompactFrontierEngine
    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)

    tuned_kw = {}
    if args.tuned_config:
        from dgc_tpu.tune import load_tuned_config

        tuned_kw = load_tuned_config(args.tuned_config).engine_kwargs(
            "ell-compact")
        # one config across 12 different seeded graphs: the hash check
        # fires by design; the point is exactness under mismatch
        warnings.filterwarnings(
            "ignore", message=".*tuned config.*", category=UserWarning)

    def compact(g):
        return CompactFrontierEngine(g, **tuned_kw)

    out = open(args.out, "w") if args.out else None
    bad = 0
    for i in range(args.draws):
        seed = args.seed0 + i
        gen = "rmat" if i % 2 else "uniform"
        t0 = time.perf_counter()
        if gen == "uniform":
            g = generate_random_graph_fast(args.nodes,
                                           avg_degree=args.avg_degree,
                                           seed=seed)
        else:
            g = generate_rmat_graph(args.nodes, avg_degree=args.avg_degree,
                                    seed=seed)
        k0 = g.max_degree + 1
        ref = BucketedELLEngine(g).attempt(k0)

        eng = compact(g)
        plain = eng.attempt(k0)
        tele = compact(g)
        tele.record_trajectory = True
        traced = tele.attempt(k0)
        s1, s2 = compact(g).sweep(k0)
        a1 = eng.attempt(k0)
        used = int(plain.colors.max()) + 1
        a2 = eng.attempt(used - 1)

        checks = {
            "colors_vs_bucketed": bool(np.array_equal(plain.colors,
                                                      ref.colors)),
            "steps_vs_bucketed": plain.supersteps == ref.supersteps,
            "telemetry_inert": bool(
                np.array_equal(plain.colors, traced.colors)
                and plain.supersteps == traced.supersteps),
            "sweep_first": bool(np.array_equal(s1.colors, a1.colors)
                                and s1.supersteps == a1.supersteps),
            "sweep_confirm": bool(
                s2 is not None and np.array_equal(s2.colors, a2.colors)
                and s2.supersteps == a2.supersteps
                and s2.status == a2.status),
        }
        rec = dict(draw=i, seed=seed, gen=gen, v=g.num_vertices,
                   max_degree=int(g.max_degree),
                   hub_buckets=compact(g).hub_buckets,
                   tuned_config=args.tuned_config,
                   seconds=round(time.perf_counter() - t0, 2), **checks)
        line = json.dumps(rec)
        print(line)
        if out:
            out.write(line + "\n")
        if not all(checks.values()):
            bad += 1
    summary = dict(draws=args.draws, mismatches=bad)
    print(json.dumps(summary))
    if out:
        out.write(json.dumps(summary) + "\n")
        out.close()
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())

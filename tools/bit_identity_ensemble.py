"""Seeded bit-identity ensemble for the segmented-gather plan (chip-free).

The segmented plan's contract is *bit-identity by construction*: same
slots, same clip widths, same ``beats_rule`` adjudication — only the
gather batching changed. This tool checks it the hard way on seeded
draws, uniform + RMAT (RMAT draws are uncapped — the heavy tail is
whatever the generator produces):

- colors AND superstep counts of the staged ``ell-compact`` engine equal
  ``ell-bucketed``'s (the bit-identity anchor the pre-PR compact engine
  was tested against, unchanged by the segmented plan — equality here is
  equality with the pre-PR compact engine);
- telemetry on == telemetry off (the trajectory carry must be inert);
- the fused ``sweep`` pair (prefix-resume included) equals two plain
  ``attempt`` calls.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/bit_identity_ensemble.py \
        [--nodes 20000] [--draws 12] [--out tools/seg_parity.jsonl]

``--tuned-config PATH`` runs every compact engine under a tuned schedule
(``dgc_tpu.tune``) instead of the shipped defaults — the tuner's knobs
are result-invariant by construction, and this is the harness that
checks it the hard way: colors AND superstep counts must still equal
``ell-bucketed``'s on every draw (``tools/tune_parity_20k.jsonl`` is a
committed run under a non-default config; the graph-shape-hash mismatch
across draws is expected and warns — schedules stay exact on any graph).

``--serve`` switches to the serving-path ensemble (``dgc_tpu.serve``):
seeded draws spanning ≥2 shape classes and mixed real sizes within a
class are ALL submitted concurrently to one micro-batching front-end
(mixed-size batches by construction), once with obs telemetry attached
and once without, and every draw's colors / minimal count / attempt
sequence must be byte-identical to the single-graph fused jump-mode
sweep (``CompactFrontierEngine`` + ``find_minimal_coloring``) — the
batched-vs-single contract ``tools/serve_parity.jsonl`` commits.

The serve ensemble runs the CONTINUOUS (lane recycling) dispatch mode
with ``--serve-slice-steps`` forced small (default 2), so every draw's
sweep crosses many slice re-entry boundaries and lanes recycle
mid-batch — the bit-identity contract is proven ACROSS recycling
boundaries, not just within one dispatch (the summary line records the
lane-recycle count as evidence recycling actually exercised).
``--serve-mode sync`` re-runs the same ensemble through the
batch-complete dispatch (the PR 5 baseline). The staged frontier
ladder runs at its shipped default (``stages="auto"``), and the draw
sizes reach the v32768 class where the ladder actually engages — so the
committed ensemble also locks bit-identity across compaction-stage
boundaries; ``--serve-device-carry`` re-runs it with the donated
device-resident carry, and ``--serve-mesh-devices N`` re-runs it with
the lane axis sharded over an N-device mesh (the committed
``serve_parity.jsonl`` is generated under a forced 8-host-device mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=8``).

``--blocked`` switches to the device-resident minimal-k ensemble
(``CompactFrontierEngine.attempt_block``): seeded uniform/RMAT draws run
the UNMODIFIED ``find_minimal_coloring`` sequentially and with
``attempts_per_dispatch=A`` (A varies across draws), in BOTH strict and
jump modes, and every leg's colors, minimal count, and full attempt
tuple sequence (budget, status, supersteps, colors_used) must be
byte-identical to the sequential driver's. Additional legs per draw:
telemetry on vs off (the blocked trajectory stack must be inert),
``attempts_per_dispatch=1`` vs flag-unset (the byte-identical
passthrough contract), and a kill-at-block-boundary checkpoint resume —
the sweep is killed after the first block's checkpoint save, resumed
from disk by a fresh engine, and the concatenated attempt sequence plus
final colors must equal the uninterrupted sequential run
(``tools/block_parity.jsonl`` is the committed run).

One JSON line per draw, nonzero exit on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import warnings


def serve_mode(args) -> int:
    import numpy as np

    from dgc_tpu.engine.compact import CompactFrontierEngine
    from dgc_tpu.engine.minimal_k import (find_minimal_coloring,
                                          make_reducer, make_validator)
    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)
    from dgc_tpu.obs import MetricsRegistry, RunLogger
    from dgc_tpu.serve.queue import ServeFrontEnd
    from dgc_tpu.serve.shape_classes import DEFAULT_LADDER

    # mixed real sizes landing in three shape classes (v2048, v8192, and
    # the STAGED v32768 class — the frontier ladder engages there, so
    # this ensemble proves bit-identity across compaction-stage
    # boundaries too), alternating uniform/RMAT — batches mix sizes
    # within a class (20k RMAT draws exceed the width ladder and take
    # the single-graph fallback: the parity contract must hold on both
    # paths)
    sizes = (1500, 2000, 5000, 8000, 20000, 24000)
    draws = []
    for i in range(args.draws):
        seed = args.seed0 + i
        v = sizes[i % len(sizes)]
        gen = "rmat" if i % 2 else "uniform"
        g = (generate_random_graph_fast(v, avg_degree=args.avg_degree,
                                        seed=seed)
             if gen == "uniform" else
             generate_rmat_graph(v, avg_degree=args.avg_degree, seed=seed))
        draws.append((i, seed, gen, g))

    def run_front_end(telemetry: bool):
        # telemetry=True runs the FULL observability stack — JSONL event
        # stream, metrics registry, request-scoped span tracing, and the
        # slice kernels' in-kernel timing variant — so the
        # telemetry_inert check locks colors/attempts byte-identical
        # with all of it on vs all of it off (the PR 7 acceptance bar)
        logger = registry = None
        if telemetry:
            import io

            logger = RunLogger(stream=io.StringIO(), echo=False)
            registry = MetricsRegistry()
        fe = ServeFrontEnd(batch_max=4, window_s=0.05,
                           queue_depth=4 * args.draws,
                           mode=args.serve_mode,
                           slice_steps=(args.serve_slice_steps
                                        if args.serve_mode == "continuous"
                                        else None),
                           device_carry=args.serve_device_carry,
                           mesh_devices=args.serve_mesh_devices,
                           timing=telemetry, trace=telemetry,
                           logger=logger, registry=registry).start()
        try:
            tickets = [fe.submit(g.arrays if hasattr(g, "arrays") else g,
                                 request_id=i) for i, _, _, g in draws]
            return ([t.result(timeout=600) for t in tickets],
                    dict(fe.scheduler.stats))
        finally:
            fe.shutdown()

    with_obs, stats_obs = run_front_end(telemetry=True)
    without_obs, _ = run_front_end(telemetry=False)

    out = open(args.out, "w") if args.out else None
    bad = 0
    for (i, seed, gen, g), r_obs, r_plain in zip(draws, with_obs,
                                                 without_obs):
        t0 = time.perf_counter()
        attempts = []
        ref = find_minimal_coloring(
            CompactFrontierEngine(g), initial_k=g.max_degree + 1,
            validate=make_validator(g),
            on_attempt=lambda res, val: attempts.append(
                (int(res.k), res.status.name, int(res.supersteps))),
            post_reduce=make_reducer(g))
        cls = DEFAULT_LADDER.class_for(g.num_vertices, g.max_degree)
        checks = {
            "colors_vs_single": bool(
                r_obs.ok and np.array_equal(r_obs.colors, ref.colors)),
            "minimal_k_vs_single": r_obs.minimal_colors == ref.minimal_colors,
            "attempts_vs_single": list(map(tuple, r_obs.attempts)) == attempts,
            "telemetry_inert": bool(
                r_plain.ok
                and np.array_equal(r_obs.colors, r_plain.colors)
                and r_obs.minimal_colors == r_plain.minimal_colors
                and r_obs.attempts == r_plain.attempts),
        }
        # informational, not a pass/fail check: fallback draws (beyond
        # the shape ladder) legitimately serve unbatched — the parity
        # contract must hold on BOTH paths
        rec = dict(draw=i, seed=seed, gen=gen, v=g.num_vertices,
                   max_degree=int(g.max_degree),
                   shape_class=cls.name if cls else None,
                   batched=bool(r_obs.batched),
                   minimal_colors=r_obs.minimal_colors,
                   seconds=round(time.perf_counter() - t0, 2), **checks)
        line = json.dumps(rec)
        print(line)
        if out:
            out.write(line + "\n")
        if not all(checks.values()):
            bad += 1
    classes = {c.name if c is not None else "fallback"
               for c in (DEFAULT_LADDER.class_for(g.num_vertices,
                                                  g.max_degree)
                         for _, _, _, g in draws)}
    summary = dict(draws=args.draws, mismatches=bad,
                   shape_classes=sorted(classes),
                   mode=args.serve_mode,
                   slice_steps=(args.serve_slice_steps
                                if args.serve_mode == "continuous"
                                else None),
                   recycles=stats_obs.get("recycles", 0),
                   slices=stats_obs.get("slices", 0),
                   stages="auto",
                   device_carry=bool(args.serve_device_carry),
                   mesh_devices=(args.serve_mesh_devices or 0),
                   telemetry="events+metrics+trace+kernel_timing")
    print(json.dumps(summary))
    if out:
        out.write(json.dumps(summary) + "\n")
        out.close()
    return 1 if bad else 0


def blocked_mode(args) -> int:
    """Device-resident minimal-k ensemble (module docstring)."""
    import shutil
    import tempfile

    import numpy as np

    from dgc_tpu.engine.compact import CompactFrontierEngine
    from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)
    from dgc_tpu.utils.checkpoint import CheckpointManager

    class _Kill(Exception):
        """Simulated crash at a block boundary."""

    def sweep(g, *, strict, attempts=1, telemetry=False, checkpoint=None,
              kill_after_blocks=None):
        eng = CompactFrontierEngine(g)
        if telemetry:
            eng.record_trajectory = True
        attempts_seen, blocks = [], [0]

        def on_block(k, a):
            if kill_after_blocks is not None \
                    and blocks[0] >= kill_after_blocks:
                raise _Kill
            blocks[0] += 1

        res = find_minimal_coloring(
            eng, initial_k=g.max_degree + 1, strict_decrement=strict,
            validate=make_validator(g),
            on_attempt=lambda r, v: attempts_seen.append(
                (int(r.k), r.status.name, int(r.supersteps),
                 int(r.colors_used))),
            checkpoint=checkpoint,
            attempts_per_dispatch=attempts, on_block=on_block)
        return res, attempts_seen

    def key(res, attempts_seen):
        return (res.minimal_colors, attempts_seen,
                None if res.colors is None else res.colors.tobytes())

    out = open(args.out, "w") if args.out else None
    bad = 0
    for i in range(args.draws):
        seed = args.seed0 + i
        gen = "rmat" if i % 2 else "uniform"
        a_per = 2 + i % 4              # A in {2,3,4,5} across the draws
        t0 = time.perf_counter()
        g = (generate_random_graph_fast(args.nodes,
                                        avg_degree=args.avg_degree,
                                        seed=seed)
             if gen == "uniform" else
             generate_rmat_graph(args.nodes, avg_degree=args.avg_degree,
                                 seed=seed))

        seq_strict = sweep(g, strict=True)
        blk_strict = sweep(g, strict=True, attempts=a_per)
        seq_jump = sweep(g, strict=False)
        blk_jump = sweep(g, strict=False, attempts=a_per)
        blk_tele = sweep(g, strict=True, attempts=a_per, telemetry=True)
        one = sweep(g, strict=True, attempts=1)

        # kill-at-block-boundary resume: the driver checkpoints once per
        # block; kill before the second block dispatches, then resume
        # from disk with a fresh engine — the concatenated attempt
        # sequence and the final colors must equal the uninterrupted run
        ckpt_dir = tempfile.mkdtemp(prefix="dgc_block_ens_")
        try:
            pre_attempts = []
            try:
                eng = CompactFrontierEngine(g)
                find_minimal_coloring(
                    eng, initial_k=g.max_degree + 1, strict_decrement=True,
                    validate=make_validator(g),
                    on_attempt=lambda r, v: pre_attempts.append(
                        (int(r.k), r.status.name, int(r.supersteps),
                         int(r.colors_used))),
                    checkpoint=CheckpointManager(ckpt_dir),
                    attempts_per_dispatch=a_per,
                    on_block=(lambda k, a, b=[0]:
                              b.__setitem__(0, b[0] + 1)
                              if b[0] < 1 else (_ for _ in ()).throw(
                                  _Kill())))
                killed = False
            except _Kill:
                killed = True
            res2, post_attempts = sweep(
                g, strict=True, attempts=a_per,
                checkpoint=CheckpointManager(ckpt_dir))
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        resume_exact = (key(res2, pre_attempts + post_attempts)
                        == key(*seq_strict)) if killed else None

        checks = {
            "strict_parity": key(*blk_strict) == key(*seq_strict),
            "jump_parity": key(*blk_jump) == key(*seq_jump),
            "telemetry_inert": key(*blk_tele) == key(*blk_strict),
            "flag_unset_identity": key(*one) == key(*seq_strict),
            # a sweep short enough to finish in one block has no
            # boundary to kill at — recorded as null, not a failure
            "resume_exact": resume_exact,
        }
        rec = dict(draw=i, seed=seed, gen=gen, v=g.num_vertices,
                   max_degree=int(g.max_degree),
                   attempts_per_dispatch=a_per,
                   strict_attempts=len(seq_strict[1]),
                   minimal_colors=seq_strict[0].minimal_colors,
                   killed_at_boundary=killed,
                   seconds=round(time.perf_counter() - t0, 2), **checks)
        line = json.dumps(rec)
        print(line)
        if out:
            out.write(line + "\n")
        if not all(v is not False for v in checks.values()):
            bad += 1
    summary = dict(draws=args.draws, mismatches=bad, mode="blocked",
                   legs=["strict_parity", "jump_parity", "telemetry_inert",
                         "flag_unset_identity", "resume_exact"])
    print(json.dumps(summary))
    if out:
        out.write(json.dumps(summary) + "\n")
        out.close()
    return 1 if bad else 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=20_000)
    p.add_argument("--draws", type=int, default=12)
    p.add_argument("--avg-degree", type=float, default=16.0)
    p.add_argument("--seed0", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    p.add_argument("--tuned-config", type=str, default=None,
                   help="tuned-config artifact applied to every compact "
                        "engine (bit-identity must hold under ANY config)")
    p.add_argument("--serve", action="store_true",
                   help="serving-path ensemble: batched front-end vs the "
                        "single-graph fused sweep (module docstring)")
    p.add_argument("--blocked", action="store_true",
                   help="device-resident minimal-k ensemble: blocked "
                        "(attempts_per_dispatch) vs sequential driver, "
                        "strict + jump, telemetry on/off, checkpoint "
                        "resume at a block boundary (module docstring)")
    p.add_argument("--serve-mode", choices=["continuous", "sync"],
                   default="continuous",
                   help="dispatch mode for --serve (default continuous — "
                        "lane recycling exercised)")
    p.add_argument("--serve-slice-steps", type=int, default=2,
                   help="continuous-mode slice size for --serve; the "
                        "small default forces many recycling boundaries "
                        "per sweep (default 2)")
    p.add_argument("--serve-device-carry", action="store_true",
                   help="run the --serve ensemble with the "
                        "device-resident carry (donated slice kernels + "
                        "on-device lane seating) — bit-identity must "
                        "hold there too")
    p.add_argument("--serve-mesh-devices", type=int, default=None,
                   help="run the --serve ensemble with the lane axis "
                        "sharded over this many local devices (the "
                        "serve CLI's --mesh-devices N; run under "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=8 on a CPU host) — colors, supersteps, "
                        "and attempt sequences must stay byte-identical "
                        "to the single-device scheduler")
    args = p.parse_args()
    if args.serve:
        return serve_mode(args)
    if args.blocked:
        return blocked_mode(args)

    import numpy as np

    from dgc_tpu.engine.bucketed import BucketedELLEngine
    from dgc_tpu.engine.compact import CompactFrontierEngine
    from dgc_tpu.models.generators import (generate_random_graph_fast,
                                           generate_rmat_graph)

    tuned_kw = {}
    if args.tuned_config:
        from dgc_tpu.tune import load_tuned_config

        tuned_kw = load_tuned_config(args.tuned_config).engine_kwargs(
            "ell-compact")
        # one config across 12 different seeded graphs: the hash check
        # fires by design; the point is exactness under mismatch
        warnings.filterwarnings(
            "ignore", message=".*tuned config.*", category=UserWarning)

    def compact(g):
        return CompactFrontierEngine(g, **tuned_kw)

    out = open(args.out, "w") if args.out else None
    bad = 0
    for i in range(args.draws):
        seed = args.seed0 + i
        gen = "rmat" if i % 2 else "uniform"
        t0 = time.perf_counter()
        if gen == "uniform":
            g = generate_random_graph_fast(args.nodes,
                                           avg_degree=args.avg_degree,
                                           seed=seed)
        else:
            g = generate_rmat_graph(args.nodes, avg_degree=args.avg_degree,
                                    seed=seed)
        k0 = g.max_degree + 1
        ref = BucketedELLEngine(g).attempt(k0)

        eng = compact(g)
        plain = eng.attempt(k0)
        tele = compact(g)
        tele.record_trajectory = True
        traced = tele.attempt(k0)
        s1, s2 = compact(g).sweep(k0)
        a1 = eng.attempt(k0)
        used = int(plain.colors.max()) + 1
        a2 = eng.attempt(used - 1)

        checks = {
            "colors_vs_bucketed": bool(np.array_equal(plain.colors,
                                                      ref.colors)),
            "steps_vs_bucketed": plain.supersteps == ref.supersteps,
            "telemetry_inert": bool(
                np.array_equal(plain.colors, traced.colors)
                and plain.supersteps == traced.supersteps),
            "sweep_first": bool(np.array_equal(s1.colors, a1.colors)
                                and s1.supersteps == a1.supersteps),
            "sweep_confirm": bool(
                s2 is not None and np.array_equal(s2.colors, a2.colors)
                and s2.supersteps == a2.supersteps
                and s2.status == a2.status),
        }
        rec = dict(draw=i, seed=seed, gen=gen, v=g.num_vertices,
                   max_degree=int(g.max_degree),
                   hub_buckets=compact(g).hub_buckets,
                   tuned_config=args.tuned_config,
                   seconds=round(time.perf_counter() - t0, 2), **checks)
        line = json.dumps(rec)
        print(line)
        if out:
            out.write(line + "\n")
        if not all(checks.values()):
            bad += 1
    summary = dict(draws=args.draws, mismatches=bad)
    print(json.dumps(summary))
    if out:
        out.write(json.dumps(summary) + "\n")
        out.close()
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env bash
# Poll the axon tunnel with cheap probes; the moment device init succeeds,
# delegate to evidence_suite.sh (battery + probes; DGC_TPU_BATTERY_ONLY=1
# for bench_suite.sh alone). Useful when the tunnel is down and the
# capture should fire unattended on recovery:
#
#   bash tools/bench_when_up.sh [outfile]
set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()
EOF
}

# status lines go through tee -a, not `>&2`: under a `2> file` redirect
# the shell's own fd offset is stale relative to content the delegated
# suite later appends (see bench_suite.sh)
until probe; do
  echo "$(date -u +%H:%M:%S) tunnel still down" | tee -a /dev/stderr >/dev/null
  sleep 240
done
echo "$(date -u +%H:%M:%S) tunnel up - starting battery" | tee -a /dev/stderr >/dev/null
# we are in the repo root (cd above), so the suite path is fixed —
# dirname "$0" would be wrong here after a relative invocation.
# evidence_suite = battery + rate probe + trace attribution + cold
# compile; set DGC_TPU_BATTERY_ONLY=1 to run just the battery.
if [ "${DGC_TPU_BATTERY_ONLY:-0}" = "1" ]; then
  exec bash tools/bench_suite.sh "$@"
fi
exec bash tools/evidence_suite.sh "$@"

#!/usr/bin/env bash
# Poll the axon tunnel with cheap probes; the moment device init succeeds,
# delegate to bench_suite.sh (the one authoritative config list). Useful
# when the tunnel is down and the battery should fire unattended on
# recovery:
#
#   bash tools/bench_when_up.sh [outfile]
set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()
EOF
}

until probe; do
  echo "$(date -u +%H:%M:%S) tunnel still down" | tee -a /dev/stderr >/dev/null
  sleep 240
done
echo "$(date -u +%H:%M:%S) tunnel up - starting battery" | tee -a /dev/stderr >/dev/null
exec bash "$(dirname "$0")/bench_suite.sh" "$@"

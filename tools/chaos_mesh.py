#!/usr/bin/env python
"""Chaos harness for failure-domain mesh resilience: seeded device-kill
schedules under a forced 8-host-device mesh.

The mesh-tier analogue of ``tools/chaos_serve.py``. Three legs, one
report, every leg on the SAME forced-device CPU mesh the committed
parity artifacts use (``XLA_FLAGS=--xla_force_host_platform_device_count
=N`` — the process re-execs itself once to get the flag in before the
first jax import, the conftest pattern):

**Leg 1 — serve-tier device-loss schedules (in-process).**
``--schedules N`` runs of the full serving stack (``ServeFrontEnd``
with ``--mesh-devices N`` + ``NetFront`` listener + ticket journal),
each under a seeded ``device_loss`` schedule; a round-robin must-cover
over the sharded points (``mesh`` = slice-boundary loss,
``serve_dispatch`` = mid-ladder loss, ``lane_seat`` = loss during
seating) guarantees every loss site is exercised. Invariant per
schedule: every accepted ticket reaches a terminal result — ``ok`` with
colors **bit-identical to the fault-free mesh run**, or a structured
failure with rc context — the run log schema-validates, and when a
fault fired the log carries a ``mesh_degrade`` and ``/healthz`` reports
the degraded mesh. Never a hang, never a wrong coloring.

**Leg 2 — single-graph re-shard sweeps (real processes).** Seeded
variants of the sharded sweep CLI (``--backend sharded --shards N
--reshard-on-loss --checkpoint-write-behind``) each under an injected
device loss — at mesh construction, mid-sweep at an attempt boundary
(strict mode, so the re-shard rung provably resumes from the
write-behind attempt checkpoint), and a chained double loss that walks
the ladder down to the single-device engines. Invariant: rc 0 with the
output coloring byte-identical to the fault-free run (or a structured
rc-114 abort — never a hang, never a wrong answer).

**Leg 3 — kill-resume while DEGRADED (``--kill-resume``).** The
``chaos_serve`` SIGKILL-at-seeded-journal-offset soak re-run with every
server incarnation started ``--mesh-devices N`` plus an injected
``device_loss`` — so the journal recovery, ticket-id high-water
resume, and byte-identical replay are proven while the mesh is
degraded, not just on the happy mesh. Zero acked-ticket loss, zero
duplicate ids, stable re-polls.

Usage::

    python tools/chaos_mesh.py --schedules 6 --sweeps 3 --kill-resume 1 \\
        --report /tmp/chaos_mesh.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHAOS_MESH_REPORT_VERSION = 1

_OUTCOMES = ("ok", "structured", "hang", "error", "mismatch")

# the sharded loss sites leg 1 must cover (round-robin):
# slice-boundary, mid-ladder dispatch, during seating
MESH_POINTS = ("mesh", "serve_dispatch", "lane_seat")


def _ensure_forced_devices(n: int) -> None:
    """Re-exec ONCE with a clean env forcing ``n`` host devices before
    any jax import (the tests/conftest.py pattern: this jax predates
    jax_num_cpu_devices, so the XLA flag must be in the environment
    before the backend initializes)."""
    flags = os.environ.get("XLA_FLAGS", "")
    forced = "xla_force_host_platform_device_count" in flags
    if os.environ.get("DGC_TPU_CHAOS_MESH_REEXEC") == "1" or (
            forced and "jax" not in sys.modules
            and os.environ.get("JAX_PLATFORMS") == "cpu"):
        return
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if not forced:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    env["DGC_TPU_CHAOS_MESH_REEXEC"] = "1"
    env["PYTHONPATH"] = REPO
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


# ---------------------------------------------------------------------------
# leg 1: serve-tier device-loss schedules
# ---------------------------------------------------------------------------

def _leg1_schedule(index: int, args):
    """Seeded device-loss schedule with a round-robin must-cover point."""
    from dgc_tpu.resilience.faults import FaultSchedule, FaultSpec

    rng = random.Random(args.seed * 77_003 + index)
    must = MESH_POINTS[index % len(MESH_POINTS)]
    specs = [FaultSpec(point=must, occurrence=rng.randint(1, 3),
                       kind="device_loss",
                       param=float(rng.randrange(args.mesh_devices)))]
    extra = FaultSchedule.random_mesh(
        rng, args.mesh_devices, n_faults=rng.randint(0, args.max_faults - 1),
        points=MESH_POINTS)
    for spec in extra:
        if any(s.point == spec.point and s.occurrence == spec.occurrence
               for s in specs):
            continue
        specs.append(spec)
    return FaultSchedule(specs), must


def _run_mesh_schedule(index: int, args, reqs, baseline) -> dict:
    """One seeded device-loss schedule against a fresh mesh stack."""
    from dgc_tpu.obs import RunLogger
    from dgc_tpu.resilience import faults
    from tools.chaos_serve import (_STRUCTURED_MARKERS, _drive_requests,
                                   _stand_stack)
    from tools.validate_runlog import validate_file

    schedule, must = _leg1_schedule(index, args)
    spec = schedule.to_spec()
    entry = {"index": index, "spec": spec, "must_cover": must,
             "fired": 0, "degrades": 0, "log_problems": 0,
             "outcome": "error"}
    workdir = tempfile.mkdtemp(prefix="dgc_chaos_mesh_")
    log = os.path.join(workdir, "run.jsonl")
    logger = RunLogger(jsonl_path=log, echo=False)
    plane = faults.FaultPlane(schedule)
    front = nf = None
    errors: list = []
    try:
        with faults.injected(plane):
            front, nf = _stand_stack(workdir, args, logger)
            tickets, results, rejects, errors = _drive_requests(
                nf.port, reqs, args.deadline)
            health = front.health()
        entry["fired"] = len(plane.fired_snapshot())
        entry["rejects"] = rejects
        if len(set(tickets)) != len(tickets):
            errors.append("duplicate ticket ids")
        structured = mismatched = 0
        for req, ticket in zip(reqs, tickets):
            doc = results.get(ticket)
            if doc is None:
                continue   # already accounted as a poll error
            if doc.get("status") == "ok":
                if doc.get("colors") != baseline[req["seed"]]:
                    mismatched += 1
            elif any(m in (doc.get("error") or "")
                     for m in _STRUCTURED_MARKERS):
                structured += 1
            else:
                errors.append(f"unstructured failure: {doc.get('error')}")
        entry["structured"] = structured
        if os.path.exists(log):
            entry["log_problems"] = len(validate_file(log))
        # a fired loss must be VISIBLE: a mesh_degrade in the stream and
        # the degraded flag in /healthz (the observability half of the
        # recovery contract)
        with open(log) as fh:
            entry["degrades"] = sum(
                1 for line in fh
                if '"event": "mesh_degrade"' in line
                or '"event":"mesh_degrade"' in line)
        if entry["fired"] and not entry["degrades"]:
            errors.append("fault fired but no mesh_degrade event")
        mesh_doc = health.get("mesh")
        if entry["fired"]:
            if not mesh_doc or not mesh_doc.get("degraded"):
                errors.append(f"/healthz mesh state not degraded after "
                              f"loss: {mesh_doc}")
        if mismatched:
            entry["outcome"] = "mismatch"
        elif errors or entry["log_problems"] or len(results) != len(tickets):
            entry["outcome"] = "error"
            entry["errors"] = errors[:5]
        else:
            entry["outcome"] = "structured" if structured else "ok"
    except RuntimeError as e:
        entry["outcome"] = "hang" if "unreachable" in str(e) else "error"
        entry["errors"] = [str(e)[:300]]
    finally:
        if nf is not None:
            nf.close()
        if front is not None:
            front.shutdown()
        logger.close()
        shutil.rmtree(workdir, ignore_errors=True)
    return entry


# ---------------------------------------------------------------------------
# leg 2: single-graph re-shard sweeps
# ---------------------------------------------------------------------------

def _sweep_cmd(args, out, extra):
    return [sys.executable, "-m", "dgc_tpu.cli",
            "--node-count", str(args.sweep_nodes),
            "--max-degree", str(args.sweep_degree),
            "--seed", str(args.seed), "--gen-method", "fast",
            "--backend", "sharded", "--shards", str(args.mesh_devices),
            "--strict-decrement",
            "--output-coloring", out] + extra


def _run_sweep_variant(index: int, args, baseline_path: str) -> dict:
    """One seeded single-graph device-loss variant: inject, run the CLI,
    demand rc 0 + byte-identical colors (or a structured rc-114)."""
    rng = random.Random(args.seed * 50_021 + index)
    dev = rng.randrange(args.mesh_devices)
    variants = (
        # loss at mesh construction: the re-shard rung rebuilds at N-1
        (f"mesh@1=device_loss:{dev}", "mesh-build"),
        # loss mid-sweep at an attempt boundary: the re-shard rung
        # resumes from the write-behind attempt checkpoint
        (f"attempt@{rng.randint(2, 4)}=device_loss:{dev}", "mid-sweep"),
        # chained double loss: primary AND re-shard rung both lose a
        # device — the ladder concedes to the single-device engines
        (f"mesh@1=device_loss:{dev},"
         f"mesh@2=device_loss:{(dev + 1) % args.mesh_devices}",
         "double-loss"),
    )
    spec, label = variants[index % len(variants)]
    entry = {"index": index, "spec": spec, "variant": label,
             "outcome": "error"}
    workdir = tempfile.mkdtemp(prefix="dgc_chaos_mesh_sweep_")
    out = os.path.join(workdir, "colors.json")
    log = os.path.join(workdir, "run.jsonl")
    cmd = _sweep_cmd(args, out, [
        "--reshard-on-loss", "--inject-faults", spec,
        "--checkpoint-dir", os.path.join(workdir, "ck"),
        "--checkpoint-write-behind", "--log-json", log])
    try:
        p = subprocess.run(cmd, cwd=REPO, env=dict(os.environ),
                           capture_output=True, text=True,
                           timeout=args.deadline)
    except subprocess.TimeoutExpired:
        entry["outcome"] = "hang"
        shutil.rmtree(workdir, ignore_errors=True)
        return entry
    entry["rc"] = p.returncode
    try:
        from tools.validate_runlog import validate_file

        entry["log_problems"] = (len(validate_file(log))
                                 if os.path.exists(log) else 0)
        if p.returncode == 114:
            # structured abort: acceptable (never a wrong answer), the
            # ladder genuinely exhausted under the schedule
            entry["outcome"] = ("structured" if not entry["log_problems"]
                                else "error")
        elif p.returncode != 0:
            entry["outcome"] = "error"
            entry["errors"] = [p.stderr[-300:]]
        else:
            with open(baseline_path) as fh:
                base = json.load(fh)
            with open(out) as fh:
                got = json.load(fh)
            if base != got:
                entry["outcome"] = "mismatch"
            elif entry["log_problems"]:
                entry["outcome"] = "error"
            else:
                entry["outcome"] = "ok"
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return entry


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def validate_chaos_mesh_report(doc) -> list[str]:
    """Structural check (the chaos_sweep/chaos_serve convention)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["report is not an object"]
    if doc.get("chaos_mesh_report_version") != CHAOS_MESH_REPORT_VERSION:
        problems.append("missing/wrong chaos_mesh_report_version")
    if not isinstance(doc.get("config"), dict):
        problems.append("missing config object")
    for leg, needs in (("schedules", ("index", "spec", "must_cover",
                                      "outcome")),
                       ("sweeps", ("index", "spec", "variant", "outcome"))):
        entries = doc.get(leg)
        if not isinstance(entries, list):
            problems.append(f"missing {leg} list")
            continue
        for i, s in enumerate(entries):
            for fieldname in needs:
                if fieldname not in s:
                    problems.append(f"{leg}[{i}]: missing {fieldname!r}")
            if s.get("outcome") not in _OUTCOMES:
                problems.append(
                    f"{leg}[{i}]: unknown outcome {s.get('outcome')!r}")
    kr = doc.get("kill_resume")
    if kr is not None and kr.get("outcome") not in _OUTCOMES:
        problems.append(f"kill_resume: unknown outcome "
                        f"{kr.get('outcome')!r}")
    summary = doc.get("summary")
    if not isinstance(summary, dict):
        problems.append("missing summary object")
    else:
        for fieldname in ("total", "ok", "structured", "failed"):
            if not isinstance(summary.get(fieldname), int):
                problems.append(f"summary: missing/invalid {fieldname!r}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--schedules", type=int, default=6,
                   help="seeded serve-tier device-loss schedules (round-"
                        "robin must-cover over mesh/serve_dispatch/"
                        "lane_seat)")
    p.add_argument("--sweeps", type=int, default=3,
                   help="seeded single-graph re-shard sweep variants "
                        "(mesh-build / mid-sweep / double-loss cycle)")
    p.add_argument("--kill-resume", type=int, default=0, metavar="KILLS",
                   help="SIGKILL/restart cycles at seeded journal "
                        "offsets with every incarnation running a "
                        "DEGRADED mesh (0 skips the leg)")
    p.add_argument("--mesh-devices", type=int, default=8,
                   help="forced host-device mesh size (default 8 — the "
                        "committed parity shape)")
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--requests-per-client", type=int, default=2)
    p.add_argument("--nodes", type=int, default=500,
                   help="vertices per serve request (>=~300 lands in "
                        "the batched shape ladder)")
    p.add_argument("--degree", type=int, default=6)
    p.add_argument("--sweep-nodes", type=int, default=300)
    p.add_argument("--sweep-degree", type=int, default=8)
    p.add_argument("--batch-max", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-faults", type=int, default=2)
    p.add_argument("--dispatch-timeout", type=float, default=5.0)
    p.add_argument("--max-lane-aborts", type=int, default=5,
                   help="quarantine budget for the stacks under test "
                        "(default 5: a request must survive a few "
                        "witnessed losses before quarantining)")
    p.add_argument("--deadline", type=float, default=240.0)
    p.add_argument("--report", default="chaos_mesh_report.json")
    p.add_argument("--workdir", default=None)
    p.add_argument("--keep-workdir", action="store_true")
    args = p.parse_args(argv)
    _ensure_forced_devices(args.mesh_devices)

    import jax

    if jax.device_count() < args.mesh_devices:
        print(f"# chaos_mesh: only {jax.device_count()} device(s) after "
              f"forcing — cannot exercise an {args.mesh_devices}-device "
              f"mesh", file=sys.stderr)
        return 2

    from tools.chaos_serve import (_baseline_colors, _request_doc,
                                   _run_kill_resume)

    reqs = [_request_doc(args.nodes, args.degree, seed=c * 10_000 + r)
            for c in range(args.clients)
            for r in range(args.requests_per_client)]
    print(f"# chaos_mesh: {len(reqs)} serve requests, mesh="
          f"{args.mesh_devices}, schedules={args.schedules}, "
          f"sweeps={args.sweeps}, kill-resume={args.kill_resume}",
          file=sys.stderr)

    schedules = []
    baseline = {}
    if args.schedules > 0 or args.kill_resume > 0:
        # fault-free baseline ON THE MESH (PR 14 proves mesh on/off
        # byte-identity; this pins the reference the faulted runs must
        # reproduce)
        baseline = _baseline_colors(args, reqs)
        print(f"# chaos_mesh: fault-free mesh baseline captured "
              f"({len(baseline)} colorings)", file=sys.stderr)
    for i in range(args.schedules):
        entry = _run_mesh_schedule(i, args, reqs, baseline)
        schedules.append(entry)
        print(f"# [serve {i}] {entry['outcome']:<12} "
              f"fired={entry['fired']} degrades={entry['degrades']} "
              f"cover={entry['must_cover']} spec={entry['spec']}",
              file=sys.stderr)

    sweeps = []
    if args.sweeps > 0:
        base_dir = tempfile.mkdtemp(prefix="dgc_chaos_mesh_base_")
        baseline_path = os.path.join(base_dir, "base.json")
        t0 = time.perf_counter()
        p0 = subprocess.run(_sweep_cmd(args, baseline_path, []), cwd=REPO,
                            env=dict(os.environ), capture_output=True,
                            text=True, timeout=args.deadline)
        if p0.returncode != 0:
            print(f"# chaos_mesh: fault-free sweep baseline failed rc "
                  f"{p0.returncode}: {p0.stderr[-300:]}", file=sys.stderr)
            return 2
        print(f"# chaos_mesh: sweep baseline in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        for i in range(args.sweeps):
            entry = _run_sweep_variant(i, args, baseline_path)
            sweeps.append(entry)
            print(f"# [sweep {i}] {entry['outcome']:<12} "
                  f"variant={entry['variant']} spec={entry['spec']}",
                  file=sys.stderr)
        shutil.rmtree(base_dir, ignore_errors=True)

    kill_resume = None
    if args.kill_resume > 0:
        # leg 3: the chaos_serve kill-resume soak with every incarnation
        # degraded — --mesh-devices plus an injected device loss ride in
        # through the server_extra hook
        rng = random.Random(args.seed * 13_009 + 5)
        kr_args = argparse.Namespace(**vars(args))
        kr_args.kills = args.kill_resume
        kr_args.server_extra = [
            "--mesh-devices", str(args.mesh_devices),
            "--inject-faults",
            f"serve_dispatch@2=device_loss:"
            f"{rng.randrange(args.mesh_devices)}"]
        kill_resume = _run_kill_resume(kr_args, reqs, baseline)
        print(f"# kill-resume (degraded): {kill_resume['outcome']} "
              f"kills={kill_resume['kills']}/"
              f"{kill_resume['kills_planned']} "
              f"restarts={kill_resume['restarts']}", file=sys.stderr)

    entries = schedules + sweeps
    ok = sum(1 for e in entries if e["outcome"] == "ok")
    structured = sum(1 for e in entries if e["outcome"] == "structured")
    failed = len(entries) - ok - structured
    if kill_resume is not None:
        if kill_resume["outcome"] == "ok":
            ok += 1
        else:
            failed += 1
    report = {
        "chaos_mesh_report_version": CHAOS_MESH_REPORT_VERSION,
        "config": {"schedules": args.schedules, "sweeps": args.sweeps,
                   "kill_resume": args.kill_resume,
                   "mesh_devices": args.mesh_devices,
                   "clients": args.clients,
                   "requests_per_client": args.requests_per_client,
                   "nodes": args.nodes, "degree": args.degree,
                   "sweep_nodes": args.sweep_nodes,
                   "sweep_degree": args.sweep_degree,
                   "seed": args.seed, "batch_max": args.batch_max,
                   "dispatch_timeout": args.dispatch_timeout,
                   "max_lane_aborts": args.max_lane_aborts},
        "schedules": schedules,
        "sweeps": sweeps,
        "kill_resume": kill_resume,
        "summary": {"total": len(entries) + (1 if kill_resume else 0),
                    "ok": ok, "structured": structured, "failed": failed},
    }
    problems = validate_chaos_mesh_report(report)
    if problems:
        for prob in problems:
            print(f"# chaos_mesh report malformed: {prob}",
                  file=sys.stderr)
        failed += 1
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"chaos_mesh": {
        "total": report["summary"]["total"], "ok": ok,
        "structured": structured, "failed": failed,
        "report": args.report}}))
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
